"""astcommon — shared AST infrastructure for the static analyzers.

concurrency_lint (ISSUE 11) grew an intra-package call-graph builder
and a tokenize-based suppression scanner; durability_lint (ISSUE 15)
needs both, byte-for-byte.  Two copies of "resolve ``self.m()`` within
the class, otherwise only names defined exactly once in the package"
would drift — the first analyzer to fix a resolution bug would
silently leave the other one wrong — so the shared halves live here
and both lints import them:

- :func:`terminal` / :data:`NO_RESOLVE` — call-name extraction and the
  builtin-method shadowing table (``int.to_bytes`` resolved to
  ``LogRecord.to_bytes`` was the prototype false positive; following a
  builtin-type method invents call chains that do not exist).
- :class:`FileInfo` / :func:`load_package` — parse every module under
  a package dir and scan its suppression comments (``# lock-ok:`` /
  ``# dur-ok:`` — the marker is a parameter) via tokenize COMMENT
  tokens, never substring-on-raw-lines: the literal marker text inside
  a docstring or error message must not become a phantom suppression
  of the next code line.  A comment-only marker line attaches to the
  next code line (audit reasons rarely fit beside the call).
- :class:`CallIndex` — name/class indices over collected functions and
  the one resolution rule (ambiguity never invents a finding).

Pure stdlib, no package imports — the suite stays millisecond-fast
with no JAX.
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from typing import Dict, List, Optional, Tuple

#: call names NEVER followed into a definition: methods of builtin
#: types (``txid.to_bytes`` is int's, ``d.get`` is dict's) shadow
#: same-named package functions, and following them invents call
#: chains that do not exist.  This also means per-record codec calls
#: (``LogRecord.from_bytes``) are not followed — deliberate:
#: record-level pickle is the log's codec and rides inside lock-held
#: read paths by design; the blocking rules target document-level
#: ``pickle.dumps``/``loads`` sites.
NO_RESOLVE = {
    "to_bytes", "from_bytes", "encode", "decode", "get", "items",
    "keys", "values", "update", "pop", "popitem", "append", "extend",
    "add", "remove", "discard", "clear", "copy", "join", "split",
    "rsplit", "strip", "replace", "format", "count", "index",
    "insert", "sort", "reverse", "setdefault", "startswith",
    "endswith", "lower", "upper", "seek", "tell", "dump", "dumps",
    "load", "loads", "send", "recv", "put", "read", "write",
}


def terminal(node: ast.expr) -> Optional[str]:
    """The terminal name of an expression: ``self.log.sync`` ->
    ``sync``, ``os`` -> ``os``; None for subscripts/calls/etc."""
    return getattr(node, "attr", getattr(node, "id", None))


class FileInfo:
    """One parsed module + its suppression comments for ``marker``."""

    def __init__(self, rel: str, tree: ast.Module, src: str,
                 marker: str):
        self.rel = rel
        self.tree = tree
        self.src = src
        self.lines = src.splitlines()
        self.marker = marker
        #: line -> suppression reason; a ``# <marker>: <reason>`` on a
        #: comment-only line attaches to the next code line
        self.suppress: Dict[int, str] = {}
        #: (comment line, reason) as written — the reason-hygiene rule
        #: reports at the comment itself
        self.suppress_sites: List[Tuple[int, str]] = []
        prefix = f"# {marker}"
        n = len(self.lines)
        try:
            toks = list(tokenize.generate_tokens(
                io.StringIO(src).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            toks = []
        for tok in toks:
            if tok.type != tokenize.COMMENT \
                    or not tok.string.startswith(prefix):
                continue
            i = tok.start[0]
            reason = tok.string.split(prefix, 1)[1] \
                .lstrip(": ").strip()
            self.suppress_sites.append((i, reason))
            target = i
            if not tok.line[:tok.start[1]].strip():
                # comment-only line: attach to the next code line
                j = i + 1
                while j <= n and (not self.lines[j - 1].strip()
                                  or self.lines[j - 1].strip()
                                  .startswith("#")):
                    j += 1
                target = j
            self.suppress.setdefault(target, reason)

    def suppressed(self, lineno: int) -> bool:
        """True when ``lineno`` carries a REASONED suppression — a
        bare marker registers as a site (for the reason-hygiene rule)
        but never suppresses."""
        return bool(self.suppress.get(lineno))


def load_package(root: str, package_dir: str, marker: str,
                 ) -> Tuple[Dict[str, FileInfo], List[str]]:
    """Parse every ``.py`` under ``root/package_dir`` into FileInfos
    keyed by repo-relative path; syntax errors come back as findings
    (the caller tags them)."""
    files: Dict[str, FileInfo] = {}
    problems: List[str] = []
    pkg = os.path.join(root, package_dir)
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "_build")]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path) as f:
                src = f.read()
            try:
                tree = ast.parse(src, filename=path)
            except SyntaxError as e:
                problems.append(f"{rel}:{e.lineno or 0}: "
                                f"[syntax] {e.msg}")
                continue
            files[rel] = FileInfo(rel, tree, src, marker)
    return files, problems


def walk_functions(tree: ast.Module):
    """Yield ``(enclosing class name or None, FunctionDef)`` for every
    function in the module, including nested defs (which get their
    own scope — their body runs at call time, not in the enclosing
    region)."""

    def walk(node, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                yield cls, child
                yield from walk(child, cls)
            else:
                yield from walk(child, cls)

    yield from walk(tree, None)


class CallIndex:
    """Name/class indices over collected function objects (anything
    with ``.name`` and ``.cls``) + the one call-resolution rule:
    ``self.m()`` resolves within the class; otherwise only names
    defined exactly once in the package resolve — ambiguity never
    invents a finding."""

    def __init__(self):
        self.by_name: Dict[str, List] = {}
        self.by_cls: Dict[Tuple[str, str], object] = {}

    def add(self, fn) -> None:
        self.by_name.setdefault(fn.name, []).append(fn)
        if fn.cls:
            self.by_cls[(fn.cls, fn.name)] = fn

    def resolve(self, caller_cls: Optional[str], name: str,
                owner: Optional[str]):
        if name in NO_RESOLVE:
            return None  # builtin-type method shadowing (see table)
        if owner == "self" and caller_cls:
            fn = self.by_cls.get((caller_cls, name))
            if fn is not None:
                return fn
        cands = self.by_name.get(name, [])
        if len(cands) == 1:
            return cands[0]
        return None

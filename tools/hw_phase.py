"""One hardware-capture phase per invocation (tools/hw_capture.py runs
these as subprocesses so a tunnel drop mid-phase kills ONE phase, not
the whole capture).  Each phase prints exactly one JSON line on stdout
as its final output; everything else goes to stderr.

Phases:
  headline_b1 / headline_b4 / headline_b8
             one coalescing variant each of the 1M-key headline sweep
             (BASELINE config 2, the north star; reads ride on b4's
             final state) — split so each fits a short tunnel window
  baselines  host CPython + native C++ per-op loops (no tunnel needed)
  entry      __graft_entry__.entry() compile + run on the live chip
  gst        config-5 GST fold at 256 DCs on the live chip

Configs 1/3/4/6 already have standalone modules (benches/configN_*.py)
and are invoked directly by the orchestrator.
"""

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _cache():
    from benches._util import enable_compile_cache

    enable_compile_cache()


def phase_headline_variant(which):
    """One coalescing variant of the headline sweep — a
    tunnel-window-sized unit the orchestrator checkpoints on its own;
    the sweep spec and shard shape come from bench.py (single source
    of truth)."""
    _cache()
    import numpy as np

    import jax

    import bench

    shape = bench.HEADLINE_SHAPE
    coalesce, gc_every, n_appends, with_reads, seed = \
        bench.headline_sweep(n_steps=20)[which]
    # the variant's OWN sweep-derived seed: the stream is identical to
    # the one bench_device builds in-process for this variant (the
    # sweep is the single source of truth for the workload too, not
    # just the shape)
    rng = np.random.default_rng(seed)
    v, stc, frontier, fetch_oh = bench.bench_variant(
        shape["K"], shape["B"], shape["D"], shape["n_dcs"],
        shape["warmup"], rng, coalesce, gc_every, n_appends)
    out = {
        "device": str(jax.devices()[0]),
        "backend": jax.default_backend(),
        "keys": shape["K"], "batch": shape["B"],
        "variant": v,
    }
    if with_reads:
        read_jnp, read_fused, read_hybrid = bench.bench_reads(
            stc, frontier, fetch_oh)
        out.update(read_jnp_s=read_jnp, read_fused_s=read_fused,
                   read_hybrid_s=read_hybrid)
    return out


def phase_baselines():
    import bench

    K = 1_000_000
    host_ops = bench.bench_host_baseline(K)
    cpp_ops = bench.bench_cpp_baseline(K, 2_000_000)
    return {"host_ops": host_ops, "cpp_ops": cpp_ops,
            "cpu_count": os.cpu_count()}


def phase_entry():
    _cache()
    import jax

    import __graft_entry__ as ge

    fn, args = ge.entry()
    from benches._util import fetch

    t0 = time.perf_counter()
    out = jax.jit(fn)(*args)
    # forced completion via one-scalar fetch INSIDE the timed window
    # (block_until_ready is not a real barrier on this tunnel —
    # benches/_util.py module doc)
    fetch(out)
    compile_s = time.perf_counter() - t0
    return {"device": str(jax.devices()[0]),
            "backend": jax.default_backend(),
            "entry_compile_run_s": compile_s}


def phase_gst():
    _cache()
    import jax

    from benches.config5_gst import summary

    return {"backend": jax.default_backend(), **summary(jax, N=256)}


def main():
    name = sys.argv[1]
    fn = {"baselines": phase_baselines,
          "entry": phase_entry, "gst": phase_gst,
          "headline_b1": lambda: phase_headline_variant("b1"),
          "headline_b4": lambda: phase_headline_variant("b4"),
          "headline_b8": lambda: phase_headline_variant("b8")}[name]
    t0 = time.time()
    out = fn()
    out["captured_at"] = t0
    out["phase_s"] = round(time.time() - t0, 1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""Static analysis gate — the dialyzer/elvis stage of the reference's
build (reference Makefile:95-96) rebuilt on the stdlib (no lint
packages ship in this environment).

Checks, per file:
- syntax (ast parse)
- unused module-level imports   [unused-import]
- bare ``except:``              [bare-except]
- mutable default arguments     [mutable-default]
- duplicate def/class names in one scope  [duplicate-def]
- ``== True`` / ``== None`` comparisons   [literal-compare]

``# noqa`` on the offending line suppresses it.  Exit status 1 on any
finding; run as:  python -m tools.analysis_gate [paths...]
The test suite runs this over the whole package
(tests/unit/test_analysis_gate.py), so the gate is part of CI the same
way the reference wires dialyzer into `make test`.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

DEFAULT_PATHS = ("antidote_tpu", "benches", "tools",
                 "bench.py", "__graft_entry__.py")


def _noqa_lines(src: str) -> set:
    return {i for i, line in enumerate(src.splitlines(), 1)
            if "# noqa" in line}


class _Scope(ast.NodeVisitor):
    """One file's findings."""

    def __init__(self, path: str, src: str):
        self.path = path
        self.noqa = _noqa_lines(src)
        self.findings: list = []
        #: alias -> (lineno, name) for module-level imports
        self.imports: dict = {}
        self.used: set = set()

    def add(self, node, code: str, msg: str) -> None:
        if node.lineno in self.noqa:
            return
        self.findings.append((self.path, node.lineno, code, msg))

    # imports (module level only: function-local lazy imports are a
    # deliberate pattern here for jax-lazy modules)
    def collect_imports(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    self.imports[alias] = (node.lineno, a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    alias = a.asname or a.name
                    self.imports[alias] = (node.lineno, a.name)

    def visit_Name(self, node: ast.Name) -> None:
        self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        root = node
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name):
            self.used.add(root.id)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self.add(node, "bare-except",
                     "bare `except:` swallows KeyboardInterrupt/SystemExit")
        self.generic_visit(node)

    def _check_defaults(self, node) -> None:
        for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                self.add(d, "mutable-default",
                         "mutable default argument is shared across calls")

    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        self._dup_check(node.body, f"{node.name}()")
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._dup_check(node.body, f"class {node.name}")
        self.generic_visit(node)

    def visit_Module(self, node):
        self._dup_check(node.body, "module")
        self.generic_visit(node)

    def _dup_check(self, body, where: str) -> None:
        seen: dict = {}
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                decorated = bool(stmt.decorator_list)
                if stmt.name in seen and not decorated \
                        and not seen[stmt.name]:
                    self.add(stmt, "duplicate-def",
                             f"{stmt.name!r} shadows an earlier "
                             f"definition in {where}")
                seen[stmt.name] = decorated

    def visit_Compare(self, node: ast.Compare) -> None:
        for op, cmp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)) and (
                    isinstance(cmp, ast.Constant)
                    and (cmp.value is None or cmp.value is True
                         or cmp.value is False)):
                self.add(node, "literal-compare",
                         "compare to None/bool with `is`, not ==/!=")
        self.generic_visit(node)


def check_file(path: Path) -> list:
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [(str(path), e.lineno or 0, "syntax", str(e.msg))]
    scope = _Scope(str(path), src)
    scope.collect_imports(tree)
    scope.visit(tree)
    # __init__ re-exports and __future__ are legitimate "unused" imports
    if path.name != "__init__.py":
        for alias, (lineno, name) in scope.imports.items():
            if name == "__future__" or alias.startswith("_"):
                continue
            if alias not in scope.used and lineno not in scope.noqa:
                scope.findings.append(
                    (str(path), lineno, "unused-import",
                     f"{name!r} imported but unused"))
    return scope.findings


def run(paths=DEFAULT_PATHS, root: Path | None = None) -> list:
    root = root or Path(__file__).resolve().parent.parent
    findings = []
    for p in paths:
        target = root / p
        files = ([target] if target.suffix == ".py"
                 else sorted(target.rglob("*.py")))
        for f in files:
            if "_pb2" in f.name or "_build" in f.parts:
                continue  # generated code
            findings.extend(check_file(f))
    return sorted(findings)


def main(argv=None) -> int:
    paths = (argv or sys.argv[1:]) or list(DEFAULT_PATHS)
    findings = run(paths)
    for path, line, code, msg in findings:
        print(f"{path}:{line}: [{code}] {msg}")
    print(f"analysis gate: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())

#!/bin/bash
# Tunnel watchdog: the remote-TPU tunnel on this rig comes and goes, so
# a one-shot bench can land in a down-window and record nothing.  This
# loop probes with a short KILLABLE jit (a wedged tunnel hangs inside
# native code); the moment a probe passes it runs the full bench and
# keeps the JSON line as BENCH_hw_selfcapture.json next to bench.py;
# exits once a non-degraded line is captured.  Paths relative to the
# repo root (the script's parent directory); scratch files under
# $WATCHDOG_TMP (default /tmp).
cd "$(dirname "$(readlink -f "$0")")/.." || exit 1
TMP=${WATCHDOG_TMP:-/tmp}
for i in $(seq 1 400); do
  if timeout 120 python -c "import jax, jax.numpy as jnp; jax.jit(lambda a:(a*2).sum())(jnp.arange(8.0)).block_until_ready()" >/dev/null 2>&1; then
    echo "$(date -u +%FT%T) tunnel UP - running bench" >> $TMP/tpu_watchdog.log
    timeout 5400 python bench.py > $TMP/bench_hw.out 2> $TMP/bench_hw.err
    rc=$?
    if grep -q '"degraded": false' $TMP/bench_hw.out 2>/dev/null; then
      cp $TMP/bench_hw.out BENCH_hw_selfcapture.json
      echo "$(date -u +%FT%T) bench captured (non-degraded)" >> $TMP/tpu_watchdog.log
      exit 0
    fi
    echo "$(date -u +%FT%T) bench ran but degraded or died (exit=$rc)" >> $TMP/tpu_watchdog.log
  else
    echo "$(date -u +%FT%T) tunnel down" >> $TMP/tpu_watchdog.log
  fi
  sleep 180
done

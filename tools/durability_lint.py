#!/usr/bin/env python
"""durability_lint — the durability-protocol analyzer (ISSUE 15).

The reference's durability plane (``logging_vnode`` over ``disk_log``)
trusts the runtime; ours is a hand-audited crash-safety discipline —
temp+fsync+rename+dir-fsync publishes, immutable checksummed segments,
manifest-rename commit points, torn-at-every-byte loaders — spread
across the fsync/rename/replace sites of oplog/, and three separate
review rounds (PRs 9, 10, 12) each found ordering bugs in it by hand:
a missing directory fsync after the truncation rename, unlink-before-
commit in compaction, stale-checkpoint adoption against rewritten
bytes.  This lint turns that review-round discipline into an AST pass
(the concurrency_lint mold, propagating through the same intra-package
call graph via tools/astcommon.py).  Five rule families, all pure-ast:

**atomic publish** [atomic-publish]: a durable artifact becomes live
by ``os.replace``/``os.rename``; the protocol is temp + flush+fsync +
rename + directory fsync.  Every rename in the package must be
preceded on the same call-graph path by an fsync of the written bytes
(``os.fsync``/``fdatasync``/``sync``/``oplog_sync``, directly or
through a resolvable call) and followed by a directory fsync
(``_fsync_dir``, ditto) — without the first, the rename can publish
bytes still in the page cache (an acked commit gone on power cut);
without the second, the rename itself can be lost (the resurrected
pre-rename inode, the exact PR-9 truncation bug).  Additionally every
``with open(..., "w"/"wb"/...)`` in the declared durable-write
modules (``_DURABLE_WRITE_MODULES`` — the table IS the policy for
what counts as a durable artifact) must reach an fsync before the
function ends: a durable write that is never fsynced is a promise the
disk does not keep.

**commit-point ordering** [commit-point]: an ``os.unlink``/
``os.remove`` of a superseded durable file must be dominated by the
rename commit point that obsoletes it — the PR-12 compaction/manifest
discipline: old segments unlink only AFTER the new manifest landed,
so a crash at any earlier byte leaves the previous manifest
authoritative over files that all still exist.  Mechanically: in any
function that performs a commit (a direct rename, or a call to a
``_COMMITTERS`` primitive), every unlink event (direct, or a call to
a ``_DELETERS`` primitive) must come after a commit event; an unlink
with no commit before it is the unlink-before-commit bug.  Functions
with no commit event are pure cleanup/retirement paths and exempt.

**immutable files** [immutable-file]: file classes declared immutable
in ``_DECLARED_IMMUTABLE`` (checkpoint seed segments, retired
``.handedoff``/``.pre-resize`` logs) must never be opened for
write/append/update outside their blessed creation modules — the
whole recovery story rests on their bytes never changing after the
manifest commit (the PR-12 stale-adoption bug was exactly rewritten
bytes under a checkpoint that believed them immutable).  Detection
follows string constants in the open's path expression, through local
assignments and one level of resolvable path-constructor calls.

**loud recovery** [loud-recovery]: exception handlers in the recovery
/load modules (``_RECOVERY_PATHS``: oplog/, the stable-meta store)
whose try block parses durable state (``pickle.loads``/``load``,
struct ``unpack``, ``from_bytes``) must raise, log, or return the
documented ``None``/sentinel refusal — a silent ``except: pass`` over
durable-state parsing recovers a half-truth as if it were everything.
Best-effort cleanup handlers (``os.remove`` and friends) are exempt:
the rule keys off what the try block READS, not that it excepts.

**torn-frame registry** [torn-frame]: every framed on-disk format
(magic + len + crc — any ``*MAGIC*`` bytes constant in the durable
modules) must be registered in ``_FRAMED_FORMATS`` with its paired
loader and the every-byte-torn test that exercises it, the way the
stats-dashboard rule pins metric families to panels.  An unregistered
magic means a writer shipped without a torn-tail story; a registered
loader or torn-test hook that no longer exists means the story
rotted.

Suppression is an audited ``# dur-ok: <reason>`` on the finding line
(or a comment-only line above it), scanned via tokenize like lock-ok;
a bare ``# dur-ok`` without a reason is itself a finding
[dur-ok-reason] — the audit trail is the point.

Runs standalone (``python tools/durability_lint.py [root]``) and as
part of ``python -m tools.static_suite``; exit 0 = clean.  Fixture
tests: tests/unit/test_durability_lint.py — including the three
historical review-round bugs as regressions each rule must catch.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import astcommon  # noqa: E402 — shared call-graph + suppression infra

#: package swept (tests and benches tear files deliberately)
PACKAGE_DIR = "antidote_tpu"

#: modules whose file WRITES are durable artifacts — the
#: write-never-fsynced check and the torn-frame magic scan run here.
#: Entries ending in "/" are directory prefixes.  The table is the
#: policy: a new module that persists durable state must be listed
#: before its writes are protocol-checked (and a module doing casual
#: file IO — obs dumps, bench outputs — stays out).  Renames, unlinks
#: and immutable-file writes are swept package-wide regardless: an
#: os.replace is a durable publish wherever it appears.
_DURABLE_WRITE_MODULES: Tuple[str, ...] = (
    "antidote_tpu/oplog/",
    "antidote_tpu/meta/stable_store.py",
    "antidote_tpu/txn/node.py",
    "antidote_tpu/cluster/node.py",
)

#: immutable file classes: path marker -> modules blessed to open
#: them for write (creation only; the defining module is NOT
#: implicitly blessed — list it).  Grow this when a new immutable
#: artifact class ships; an empty tuple means NOBODY writes one in
#: place (they are created only by rename).
_DECLARED_IMMUTABLE: Dict[str, Tuple[str, ...]] = {
    # checkpoint seed segments: immutable once a manifest lists them
    # (checkpoint.py creates them and installs shipped copies)
    ".seg-": ("antidote_tpu/oplog/checkpoint.py",),
    # retired logs displaced by a handoff cutover / ring resize: kept
    # as forensic history, never reopened for write
    ".handedoff": (),
    ".pre-resize": (),
}

#: recovery/load modules for the loud-recovery sweep ("/" suffix =
#: directory prefix): where a swallowed parse failure recovers a
#: half-truth as if it were everything
_RECOVERY_PATHS: Tuple[str, ...] = (
    "antidote_tpu/oplog/",
    "antidote_tpu/meta/stable_store.py",
)

#: call names that PARSE durable state (deserialization, not raw IO:
#: a retry loop around a raw read is not a parse path)
_PARSE_CALLS = {"loads", "load", "unpack", "from_bytes"}

#: terminal call names that are an fsync of written bytes
_FSYNC_NAMES = {"fsync", "fdatasync", "sync", "oplog_sync"}

#: the one directory-fsync primitive (oplog/log._fsync_dir — "the ONE
#: copy of this discipline", its docstring says; this rule holds the
#: package to that)
_DIR_FSYNC_NAME = "_fsync_dir"

#: repo primitives that ARE a commit point (they rename internally) —
#: commit-point ordering counts a call to one as the commit event
_COMMITTERS = {"write_doc", "install_bundle", "commit_truncate"}

#: repo primitives that unlink durable files wholesale — counted as
#: unlink events by commit-point ordering
_DELETERS = {"delete_checkpoint_files", "_sweep_segments"}

#: open() modes that write (read-only modes are never a finding)
_WRITE_MODE_CHARS = ("w", "a", "+", "x")

#: framed on-disk formats: (module rel, magic var name) -> contract.
#: ``loader`` must be a function in the same module; ``torn_test``
#: must exist and contain ``torn_hook`` (the every-byte-torn test
#: name).  Registering here is part of shipping a framed writer.
_FRAMED_FORMATS: Dict[Tuple[str, str], Dict[str, str]] = {
    ("antidote_tpu/oplog/checkpoint.py", "_MAGIC"): {
        "loader": "_parse",
        "torn_test": "tests/unit/test_checkpoint.py",
        "torn_hook": "test_truncated_at_every_byte_loads_previous_or_none",
    },
    ("antidote_tpu/oplog/checkpoint.py", "_SEG_MAGIC"): {
        "loader": "_load_segment",
        "torn_test": "tests/unit/test_ckpt_segments.py",
        "torn_hook": "test_torn_segment_at_every_byte_refuses_whole_checkpoint",
    },
    ("antidote_tpu/oplog/log.py", "_TRUNC_MAGIC"): {
        "loader": "_parse_trunc_marker",
        "torn_test": "tests/unit/test_oplog.py",
        "torn_hook": "test_trunc_marker_torn_at_every_byte_reads_base_zero",
    },
}


def _in_paths(rel: str, paths: Tuple[str, ...]) -> bool:
    return any(rel.startswith(p) if p.endswith("/") else rel == p
               for p in paths)


def _is_write_mode(mode: str) -> bool:
    return any(c in mode for c in _WRITE_MODE_CHARS)


class _Func:
    """One function's durability events, line-ordered."""

    def __init__(self, rel: str, cls: Optional[str], node):
        self.rel = rel
        self.cls = cls
        self.node = node
        self.name = node.name
        #: direct rename lines (os.replace / os.rename)
        self.renames: List[int] = []
        #: direct unlink lines (os.remove / os.unlink)
        self.unlinks: List[int] = []
        #: direct fsync lines (_FSYNC_NAMES terminals)
        self.fsyncs: List[int] = []
        #: direct directory-fsync lines (_fsync_dir)
        self.dir_fsyncs: List[int] = []
        #: ``with open(..., <write mode>)`` lines
        self.writes: List[int] = []
        #: every call site: (name, owner, lineno)
        self.calls: List[Tuple[str, Optional[str], int]] = []

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


class _Analyzer:
    def __init__(self, root: str):
        self.root = root
        self.files: Dict[str, astcommon.FileInfo] = {}
        self.funcs: List[_Func] = []
        self.calls = astcommon.CallIndex()

    # ------------------------------------------------------------ parse

    def load(self) -> List[str]:
        self.files, problems = astcommon.load_package(
            self.root, PACKAGE_DIR, marker="dur-ok")
        for rel in sorted(self.files):
            info = self.files[rel]
            for cls, node in astcommon.walk_functions(info.tree):
                fn = _Func(rel, cls, node)
                self.funcs.append(fn)
                self._scan_func(fn)
        for fn in self.funcs:
            self.calls.add(fn)
        return problems

    def _scan_func(self, fn: _Func) -> None:
        """Collect one function's durability events; nested defs are
        skipped (they scan as their own functions)."""

        def visit(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        ctx = item.context_expr
                        if isinstance(ctx, ast.Call) \
                                and astcommon.terminal(ctx.func) \
                                == "open" \
                                and self._open_mode(ctx) is not None \
                                and _is_write_mode(
                                    self._open_mode(ctx)):
                            fn.writes.append(ctx.lineno)
                if isinstance(child, ast.Call):
                    name = astcommon.terminal(child.func)
                    owner = astcommon.terminal(child.func.value) \
                        if isinstance(child.func, ast.Attribute) \
                        else None
                    if name:
                        ln = child.lineno
                        if owner == "os" and name in ("replace",
                                                      "rename"):
                            fn.renames.append(ln)
                        elif owner == "os" and name in ("remove",
                                                        "unlink"):
                            fn.unlinks.append(ln)
                        elif name in _FSYNC_NAMES and fn.name != name:
                            # a function NAMED like the barrier is its
                            # definition/wrapper, not an event site
                            fn.fsyncs.append(ln)
                        elif name == _DIR_FSYNC_NAME \
                                and fn.name != name:
                            fn.dir_fsyncs.append(ln)
                        fn.calls.append((name, owner, ln))
                visit(child)

        visit(fn.node)

    @staticmethod
    def _open_mode(call: ast.Call) -> Optional[str]:
        """The literal mode of an ``open()`` call, or None when absent
        /non-constant (a computed mode never invents a finding)."""
        mode = None
        if len(call.args) >= 2:
            mode = call.args[1]
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if isinstance(mode, ast.Constant) and isinstance(mode.value,
                                                        str):
            return mode.value
        return None

    # --------------------------------------------- transitive IO facts

    def _transitive(self) -> Dict[_Func, Set[str]]:
        """func -> subset of {"fsync", "dirfsync"} reachable through
        resolvable calls — how a helper's fsync covers its caller's
        publish path (the same propagation that found the PR-8 hidden
        fsync, pointed the other way: here reachability SATISFIES the
        protocol instead of violating it).

        Cycle discipline: a DFS that hits a function already on the
        stack returns a LOWER BOUND (the back edge is cut), and
        memoizing that bound would let one member of a call cycle
        poison every caller's fact set — a rename whose acyclic path
        reaches an fsync would be falsely flagged (missing facts here
        INVENT findings, the opposite polarity of concurrency_lint's
        blocking propagation, where missing facts only miss them).
        So cut-tainted results are returned but never memoized; each
        top-level traversal starts from an empty stack, visits every
        reachable function once, and is therefore exact."""
        memo: Dict[_Func, Set[str]] = {}

        def go(fn: _Func, stack: Set[_Func]
               ) -> Tuple[Set[str], bool]:
            if fn in memo:
                return memo[fn], True
            if fn in stack:
                return set(), False  # cycle cut: lower bound
            stack.add(fn)
            out: Set[str] = set()
            clean = True
            if fn.fsyncs:
                out.add("fsync")
            if fn.dir_fsyncs:
                out.add("dirfsync")
            for (name, owner, _ln) in fn.calls:
                callee = self.calls.resolve(fn.cls, name, owner)
                if callee is not None and callee is not fn:
                    sub, sub_clean = go(callee, stack)
                    out |= sub
                    clean = clean and sub_clean
            stack.discard(fn)
            if clean:
                memo[fn] = out
            return out, clean

        exact: Dict[_Func, Set[str]] = {}
        for fn in self.funcs:
            exact[fn] = go(fn, set())[0]
        return exact

    def _event_lines(self, fn: _Func, trans, fact: str,
                     direct: List[int]) -> List[int]:
        """Lines where ``fact`` holds: direct events plus call sites
        whose callee transitively performs it."""
        out = list(direct)
        for (name, owner, ln) in fn.calls:
            callee = self.calls.resolve(fn.cls, name, owner)
            if callee is not None and callee is not fn \
                    and fact in trans.get(callee, ()):
                out.append(ln)
        return sorted(out)

    # ------------------------------------------- rule 1: atomic-publish

    def lint_atomic_publish(self) -> List[str]:
        problems: List[str] = []
        trans = self._transitive()
        for fn in self.funcs:
            info = self.files[fn.rel]
            if not (fn.renames or fn.writes):
                continue
            fsync_lines = self._event_lines(fn, trans, "fsync",
                                            fn.fsyncs)
            dirf_lines = self._event_lines(fn, trans, "dirfsync",
                                           fn.dir_fsyncs)
            for ln in fn.renames:
                if info.suppressed(ln):
                    continue
                if not any(f < ln for f in fsync_lines):
                    problems.append(
                        f"{fn.rel}:{ln}: [atomic-publish] rename "
                        f"publishes bytes never fsynced ({fn.qual}) — "
                        "flush+fsync the written temp before the "
                        "rename, or audit with `# dur-ok: <reason>`")
                if not any(d > ln for d in dirf_lines):
                    problems.append(
                        f"{fn.rel}:{ln}: [atomic-publish] rename "
                        f"without a directory fsync ({fn.qual}) — a "
                        "power cut can resurrect the pre-rename "
                        "inode; call _fsync_dir after the rename, or "
                        "audit with `# dur-ok: <reason>`")
            if _in_paths(fn.rel, _DURABLE_WRITE_MODULES):
                for ln in fn.writes:
                    if info.suppressed(ln):
                        continue
                    if not any(f >= ln for f in fsync_lines):
                        problems.append(
                            f"{fn.rel}:{ln}: [atomic-publish] durable "
                            f"write is never fsynced ({fn.qual}) — "
                            "the bytes live only in the page cache; "
                            "fsync before anything depends on them, "
                            "or audit with `# dur-ok: <reason>`")
        return problems

    # -------------------------------------------- rule 2: commit-point

    def lint_commit_point(self) -> List[str]:
        problems: List[str] = []
        for fn in self.funcs:
            info = self.files[fn.rel]
            commits = list(fn.renames)
            unlinks = [(ln, "os.remove/os.unlink")
                       for ln in fn.unlinks]
            for (name, _owner, ln) in fn.calls:
                if name in _COMMITTERS:
                    commits.append(ln)
                elif name in _DELETERS:
                    unlinks.append((ln, f"{name}()"))
            if not commits:
                continue  # pure cleanup/retirement path: exempt
            for (ln, what) in sorted(unlinks):
                if info.suppressed(ln):
                    continue
                if not any(c < ln for c in commits):
                    problems.append(
                        f"{fn.rel}:{ln}: [commit-point] {what} "
                        f"unlinks a durable file BEFORE this "
                        f"function's commit point lands ({fn.qual}) — "
                        "a crash between them loses both the old "
                        "file and the commit; unlink only after the "
                        "rename, or audit with `# dur-ok: <reason>`")
        return problems

    # ------------------------------------------ rule 3: immutable-file

    def lint_immutable(self) -> List[str]:
        problems: List[str] = []
        for rel in sorted(self.files):
            info = self.files[rel]
            for cls, node in astcommon.walk_functions(info.tree):
                for call in ast.walk(node):
                    if not (isinstance(call, ast.Call)
                            and astcommon.terminal(call.func)
                            == "open"):
                        continue
                    mode = self._open_mode(call)
                    if mode is None or not _is_write_mode(mode):
                        continue
                    if not call.args:
                        continue
                    consts = self._path_constants(
                        call.args[0], node, cls)
                    for marker, blessed in sorted(
                            _DECLARED_IMMUTABLE.items()):
                        if not any(marker in c for c in consts):
                            continue
                        if rel in blessed:
                            continue
                        if info.suppressed(call.lineno):
                            continue
                        who = ", ".join(blessed) or \
                            "nobody — this class is created only " \
                            "by rename"
                        problems.append(
                            f"{rel}:{call.lineno}: [immutable-file] "
                            f"opens a {marker!r} file with mode "
                            f"{mode!r} outside its blessed creation "
                            f"module(s) ({who}) — immutable "
                            "artifacts must never be rewritten in "
                            "place (the PR-12 stale-adoption "
                            "lesson); recovery trusts their bytes")
        return problems

    def _path_constants(self, expr: ast.expr, func_node,
                        cls: Optional[str]) -> List[str]:
        """String constants reachable from a path expression: its own
        subtree, the subtree assigned to a Name it references (local
        dataflow, one level), and the body of a resolvable path-
        constructor it calls (one level) — enough to see through
        ``path = self._seg_path(seq)`` without real dataflow."""
        out: List[str] = []

        def consts_of(e) -> None:
            for n in ast.walk(e):
                if isinstance(n, ast.Constant) \
                        and isinstance(n.value, str):
                    out.append(n.value)

        consts_of(expr)
        names = {n.id for n in ast.walk(expr)
                 if isinstance(n, ast.Name)}
        for n in ast.walk(func_node):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Name) and t.id in names:
                        consts_of(n.value)
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                name = astcommon.terminal(n.func)
                owner = astcommon.terminal(n.func.value) \
                    if isinstance(n.func, ast.Attribute) else None
                callee = self.calls.resolve(cls, name, owner) \
                    if name else None
                if callee is not None:
                    consts_of(callee.node)
        # one level deeper: calls inside the resolved assignments
        # (``path = self._seg_path(seq)`` -> _seg_path's f-string)
        for n in ast.walk(func_node):
            if isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id in names
                    for t in n.targets):
                for c in ast.walk(n.value):
                    if isinstance(c, ast.Call):
                        name = astcommon.terminal(c.func)
                        owner = astcommon.terminal(c.func.value) \
                            if isinstance(c.func, ast.Attribute) \
                            else None
                        callee = self.calls.resolve(cls, name, owner) \
                            if name else None
                        if callee is not None:
                            consts_of(callee.node)
        return out

    # ----------------------------------------- rule 4: loud-recovery

    def lint_loud_recovery(self) -> List[str]:
        problems: List[str] = []
        for rel in sorted(self.files):
            if not _in_paths(rel, _RECOVERY_PATHS):
                continue
            info = self.files[rel]
            for node in ast.walk(info.tree):
                if not isinstance(node, ast.Try):
                    continue
                if not self._try_parses_durable_state(node):
                    continue
                for handler in node.handlers:
                    if self._handler_is_loud(handler):
                        continue
                    if info.suppressed(handler.lineno):
                        continue
                    problems.append(
                        f"{rel}:{handler.lineno}: [loud-recovery] "
                        "silent exception handler over durable-state "
                        "parsing — recovery must raise, log, or "
                        "return the documented refusal; a swallowed "
                        "parse failure serves a half-truth as "
                        "everything (audit with `# dur-ok: <reason>` "
                        "only if the swallow is the contract)")
        return problems

    @staticmethod
    def _try_parses_durable_state(node: ast.Try) -> bool:
        for sub in node.body:
            for n in ast.walk(sub):
                if isinstance(n, ast.Call) and \
                        astcommon.terminal(n.func) in _PARSE_CALLS:
                    return True
        return False

    @staticmethod
    def _handler_is_loud(handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(handler):
            if isinstance(n, (ast.Raise, ast.Return)):
                return True
            if isinstance(n, ast.Call):
                name = astcommon.terminal(n.func)
                owner = astcommon.terminal(n.func.value) \
                    if isinstance(n.func, ast.Attribute) else None
                if owner in ("log", "logger", "logging") or name in (
                        "error", "warning", "exception", "critical"):
                    return True
        return False

    # ------------------------------------------- rule 5: torn-frame

    def lint_torn_frame(self) -> List[str]:
        problems: List[str] = []
        seen: Set[Tuple[str, str]] = set()
        for rel in sorted(self.files):
            if not _in_paths(rel, _DURABLE_WRITE_MODULES):
                continue
            info = self.files[rel]
            for node in ast.walk(info.tree):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, bytes)):
                    continue
                for t in node.targets:
                    if not (isinstance(t, ast.Name)
                            and "MAGIC" in t.id.upper()):
                        continue
                    key = (rel, t.id)
                    seen.add(key)
                    if key not in _FRAMED_FORMATS:
                        problems.append(
                            f"{rel}:{node.lineno}: [torn-frame] "
                            f"framed-format magic {t.id} is not "
                            "registered in _FRAMED_FORMATS — a framed "
                            "writer ships WITH its paired loader and "
                            "an every-byte-torn test (the registry is "
                            "the contract)")
        # registry drift: only entries whose module is in THIS tree
        # (fixture roots carry none of the real modules)
        for (rel, var), contract in sorted(_FRAMED_FORMATS.items()):
            info = self.files.get(rel)
            if info is None:
                continue
            if (rel, var) not in seen:
                problems.append(
                    f"{rel}: [torn-frame] registered magic {var} no "
                    "longer exists — prune the _FRAMED_FORMATS entry "
                    "or restore the format")
                continue
            loader = contract["loader"]
            if not any(node.name == loader for _cls, node
                       in astcommon.walk_functions(info.tree)):
                problems.append(
                    f"{rel}: [torn-frame] registered loader "
                    f"{loader}() for {var} not found in the module — "
                    "the torn-frame pairing rotted")
            test_path = os.path.join(self.root, contract["torn_test"])
            hook = contract["torn_hook"]
            if not os.path.exists(test_path):
                problems.append(
                    f"{contract['torn_test']}: [torn-frame] torn test "
                    f"file for {var} is missing")
            else:
                with open(test_path) as f:
                    if hook not in f.read():
                        problems.append(
                            f"{contract['torn_test']}: [torn-frame] "
                            f"every-byte-torn hook {hook} for {var} "
                            "not found — the loader is no longer "
                            "exercised against torn frames")
        return problems

    # --------------------------------------- suppression reason hygiene

    def lint_dur_ok_reasons(self) -> List[str]:
        """A ``# dur-ok`` with no reason defeats the audit trail the
        suppression exists to create — itself a finding."""
        problems = []
        for rel in sorted(self.files):
            for ln, reason in self.files[rel].suppress_sites:
                if not reason:
                    problems.append(
                        f"{rel}:{ln}: [dur-ok-reason] `# dur-ok` "
                        "without a reason — write `# dur-ok: <why "
                        "this site may deviate from the durability "
                        "protocol>`")
        return problems


def lint(root: str) -> List[str]:
    an = _Analyzer(root)
    problems = an.load()
    problems.extend(an.lint_atomic_publish())
    problems.extend(an.lint_commit_point())
    problems.extend(an.lint_immutable())
    problems.extend(an.lint_loud_recovery())
    problems.extend(an.lint_torn_frame())
    problems.extend(an.lint_dur_ok_reasons())
    return problems


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else repo_root()
    problems = lint(root)
    if problems:
        print(f"durability_lint: {len(problems)} finding(s):",
              file=sys.stderr)
        for p in problems:
            print(f"  {p}", file=sys.stderr)
        return 1
    print("durability_lint: OK — publish protocol, commit-point "
          "ordering, immutable files, recovery loudness, and the "
          "torn-frame registry are clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

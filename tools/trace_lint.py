#!/usr/bin/env python
"""trace_lint — instrumentation-coverage check for the obs plane.

ISSUE 1 threads txid-correlated spans (antidote_tpu/obs/spans.py) and
profiler annotations (antidote_tpu/obs/prof.py; tracing.py is a shim)
through every public entry point of the coordinator, device plane,
log, and inter-DC planes.  Instrumentation rots silently: a refactor
that drops a ``with tracer.span(...)`` breaks no test, it just blinds
the next forensic hunt.  This lint pins the contract — every entry
point listed in ENTRY_POINTS must carry a span, an instant, a profiler
annotation, or the @traced decorator — and fails loudly when one goes
dark.

ISSUE 2 adds the device-kernel rule: every PUBLIC ``@jax.jit``-
decorated function under antidote_tpu/mat/ must also carry a
``@kernel_span`` (antidote_tpu/obs/prof.py) so per-kernel timing and
compile-cache-miss attribution cannot silently go dark when a new
jitted entry point lands.  ISSUE 3 extends the same rule to
antidote_tpu/interdc/ — the dependency gate's resident-ring kernels
(interdc/gate_kernels.py) are now a first-class device plane.

ISSUE 6 adds the publish rule: every function under
antidote_tpu/interdc/ that calls ``transport.publish`` / ``bus.publish``
(the pub/sub fabric's send) must carry a span or instant — the async
ship worker moved publishing off the commit path, and an untraced
publish site would make outbound frames invisible to the txid-
correlated forensic hunts the obs plane exists for.

ISSUE 7 adds the decode rule (the receive-side mirror of the publish
rule): every function under antidote_tpu/interdc/ or
antidote_tpu/cluster/ that DECODES a wire frame (``frame_from_bin`` /
``*.from_bin``) must record the arrival instant with a span/instant —
the visibility-latency SLOs subtract the carried origin-commit
wallclock from arrival-side time, so an untraced decode site is a
blind spot in every journey it feeds.  The decoder definitions
themselves (functions *named* frame_from_bin / from_bin) are exempt:
the rule binds call sites, where arrival happens.

ISSUE 8 adds the fused-read rule: every function under
antidote_tpu/mat/ that calls ``fused_read`` (the multi-fold one-
dispatch device program) must carry a span/instant — the read serve
plane moved these dispatches off the per-transaction call stack, and
an untraced gathered fold would make the hottest read-path kernel
invisible to the serve-stage latency panels and sampled txn trees.
The definition itself (a function *named* fused_read) is exempt; call
sites are not.

ISSUE 9 adds the sync rule: every function under antidote_tpu/oplog/
that calls ``sync()`` / ``fsync`` (/ the native ``oplog_sync``) must
carry a span or instant — the group-commit plane moved the fsync off
the partition lock and between threads, and an untraced durability
barrier would blind exactly the stall hunts the log_sync_wait /
log_group_drain timeline exists for.  Functions NAMED like the
barrier (``sync`` — the DurableLog/_PyLog definitions) are exempt;
call sites are not.

ISSUE 10 adds the checkpoint-IO rule: every function under
antidote_tpu/oplog/ that performs checkpoint IO — writing/loading the
checkpoint document (``write_doc`` / ``load_doc``) or truncating the
log (``truncate_below``) — must carry a span or instant.  These are
the cold-path disk moves recovery-time and retention forensics hinge
on (ckpt_write/ckpt_load spans, the log_truncate span, the CKPT_*
gauges), and they run from commit tails and remote bootstrap answers
— an untraced site would make a multi-second checkpoint stall
unattributable.  The IO definitions themselves (functions NAMED
write_doc / load_doc / truncate_below) are exempt; call sites are not.

Runs standalone (``python tools/trace_lint.py``) and from tier-1
(tests/unit/test_trace_lint.py); exit code 0 = fully instrumented.
Purely static (ast), so it needs no JAX and runs in milliseconds.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List

#: (relative module path) -> {class name: [method, ...]} — the public
#: entry points of each plane that MUST be instrumented.  Grow this
#: list when a PR adds a plane; never shrink it to silence the lint.
ENTRY_POINTS: Dict[str, Dict[str, List[str]]] = {
    "antidote_tpu/txn/coordinator.py": {
        "Coordinator": ["read_objects", "update_objects",
                        "commit_transaction", "abort_transaction"],
    },
    "antidote_tpu/oplog/partition.py": {
        "PartitionLog": ["append_commit"],
    },
    "antidote_tpu/mat/device_plane.py": {
        "DevicePlane": ["stage", "read", "read_many", "gc", "flush"],
    },
    "antidote_tpu/mat/sharded.py": {
        "_ShardedBase": ["append", "read", "read_keys"],
    },
    "antidote_tpu/interdc/sender.py": {
        "InterDcLogSender": ["on_append"],
    },
    "antidote_tpu/interdc/dep.py": {
        "DependencyGate": ["_apply"],
    },
    "antidote_tpu/interdc/dc.py": {
        "DataCenter": ["_deliver"],
    },
}

#: a call to <obj>.<attr> counts as instrumentation when (obj, attr)
#: is one of these — the span/annotation surfaces of the obs plane
#: (the tracing.annotate shim form was retired with tracing.py,
#: ISSUE 7; prof.annotate is the home)
_INSTRUMENTED_CALLS = {
    ("tracer", "span"), ("tracer", "instant"),
    ("prof", "annotate"),
}

#: packages whose public @jax.jit functions must carry @kernel_span
#: (ISSUE 2 for mat/, ISSUE 3 for interdc/ — the device-plane
#: profiler's coverage contract; grow this tuple when a new package
#: gains jitted entry points, never shrink it)
_KERNEL_SPAN_DIRS = (os.path.join("antidote_tpu", "mat"),
                     os.path.join("antidote_tpu", "interdc"))

#: decorators that wrap the whole method in a span
_INSTRUMENTED_DECORATORS = {"traced"}

#: attribute names that hold the inter-DC pub/sub fabric: a call
#: ``<something>.<one of these>.publish(...)`` (or a bare
#: ``transport.publish`` / ``bus.publish``) is a wire send and must be
#: instrumented (ISSUE 6); the package the rule sweeps
_PUBLISH_OWNERS = ("transport", "bus")
_PUBLISH_DIR = os.path.join("antidote_tpu", "interdc")

#: wire-frame decoder call names: a call to one of these (bare or as
#: an attribute — ``frame_from_bin(data)`` / ``InterDcTxn.from_bin(b)``)
#: marks the function as a frame-arrival site (ISSUE 7); the dirs the
#: rule sweeps
_DECODE_NAMES = ("frame_from_bin", "from_bin")
_DECODE_DIRS = (os.path.join("antidote_tpu", "interdc"),
                os.path.join("antidote_tpu", "cluster"))

#: gathered-fold call names: a call to one of these under mat/ (bare
#: or as an attribute) is a serve-side one-dispatch device fold and
#: must be instrumented (ISSUE 8); definitions are exempt like the
#: decode rule's
_FUSED_NAMES = ("fused_read",)
_FUSED_DIRS = (os.path.join("antidote_tpu", "mat"),)

#: durability-barrier call names under oplog/ (ISSUE 9): a call whose
#: terminal name is one of these is an fsync (or the flush+fsync
#: wrapper) and the calling function must be instrumented; functions
#: NAMED "sync" are the barrier definitions themselves and are exempt
_SYNC_NAMES = ("sync", "fsync", "oplog_sync")
_SYNC_DIR = os.path.join("antidote_tpu", "oplog")

#: checkpoint-IO call names under oplog/ (ISSUE 10): a call whose
#: terminal name is one of these moves checkpoint/retention state on
#: disk and the calling function must be instrumented; functions NAMED
#: like the IO primitives are the definitions themselves and exempt
_CKPT_IO_NAMES = ("write_doc", "load_doc", "truncate_below")
_CKPT_DIR = os.path.join("antidote_tpu", "oplog")


def _is_instrumented(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = getattr(target, "attr", getattr(target, "id", None))
        if name in _INSTRUMENTED_DECORATORS:
            return True
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and (f.value.id, f.attr) in _INSTRUMENTED_CALLS):
            return True
    return False


def _is_jax_jit(dec: ast.expr) -> bool:
    """True for ``@jax.jit``, ``@jit`` (from-imported), either with a
    call ``(...)``, and ``@[functools.]partial([jax.]jit, ...)``
    decorator forms.  The bare-name match can in principle catch a
    foreign ``jit`` (numba's), but under antidote_tpu/mat/ any jit is
    jax's — a false positive here is a lint nudge, not a build break."""
    if isinstance(dec, ast.Attribute):
        return (dec.attr == "jit" and isinstance(dec.value, ast.Name)
                and dec.value.id == "jax")
    if isinstance(dec, ast.Name):
        return dec.id == "jit"
    if isinstance(dec, ast.Call):
        f = dec.func
        name = getattr(f, "attr", getattr(f, "id", None))
        if name == "partial" and dec.args:
            return _is_jax_jit(dec.args[0])
        if name == "jit":
            return _is_jax_jit(f)
    return False


def _has_kernel_span(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if getattr(target, "attr",
                   getattr(target, "id", None)) == "kernel_span":
            return True
    return False


def _call_name(node: ast.expr):
    if isinstance(node, ast.Call):
        f = node.func
        return getattr(f, "attr", getattr(f, "id", None))
    return None


def _unwrapped_jit_assign(value: ast.expr) -> bool:
    """True when a module-level assignment VALUE is a bare jitted
    callable — ``jax.jit(f)`` / ``partial(jax.jit, ...)`` — with no
    kernel_span / profiler.wrap layer around it.  ISSUE 4 extends the
    lint here: the ingest plane's flush kernels are natural to land as
    ``flush = jax.jit(_impl)`` assignments, which the decorator-only
    rule never saw — an unprofiled flush kernel must not land either
    way.  ``kernel_span(...)(jax.jit(f))`` (store.py's
    _orset_gc_nodonate idiom, public form) and ``profiler.wrap(...)``
    both count as instrumented."""
    if not isinstance(value, ast.Call):
        return False
    if _is_jax_jit(value):
        return True
    name = _call_name(value)
    if name in ("kernel_span", "wrap"):
        return False  # instrumented wrapper
    # kernel_span("...")(jax.jit(f)): outer call whose func is a call
    if isinstance(value.func, ast.Call) \
            and _call_name(value.func) == "kernel_span":
        return False
    # partial(jax.jit, ...)(impl): the func itself is a jit factory
    if isinstance(value.func, ast.Call) and _is_jax_jit(value.func):
        return True
    # any other wrapper around a jit call still hides an unprofiled
    # kernel: look one level into the arguments
    return any(isinstance(a, ast.Call) and _is_jax_jit(a)
               for a in value.args)


def lint_kernel_spans(root: str) -> List[str]:
    """ISSUE 2/3 rule: public @jax.jit functions under the device-
    plane packages (mat/, interdc/) must carry @kernel_span so the
    profiler sees them.  ISSUE 4 extends the same contract to public
    module-level ``NAME = jax.jit(...)`` assignments (the ingest
    module's flush-kernel form)."""
    problems: List[str] = []
    for rel_dir in _KERNEL_SPAN_DIRS:
        d = os.path.join(root, rel_dir)
        if not os.path.isdir(d):
            continue
        for fname in sorted(os.listdir(d)):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(d, fname)
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            for node in tree.body:
                if isinstance(node, ast.FunctionDef):
                    if node.name.startswith("_"):
                        continue
                    if any(_is_jax_jit(dec)
                           for dec in node.decorator_list) \
                            and not _has_kernel_span(node):
                        problems.append(
                            f"{rel_dir}/{fname}::{node.name}: public "
                            "@jax.jit entry point without @kernel_span "
                            "— its timing and compile-miss attribution "
                            "are dark (antidote_tpu/obs/prof.py)")
                elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    names = [t.id for t in targets
                             if isinstance(t, ast.Name)]
                    if not names or all(n.startswith("_")
                                        for n in names):
                        continue
                    if node.value is not None \
                            and _unwrapped_jit_assign(node.value):
                        problems.append(
                            f"{rel_dir}/{fname}::{names[0]}: public "
                            "jitted assignment without kernel_span/"
                            "profiler.wrap — unprofiled flush kernels "
                            "cannot land (antidote_tpu/obs/prof.py)")
    return problems


def _is_publish_call(node: ast.Call) -> bool:
    """True for ``transport.publish(...)`` / ``self.bus.publish(...)``
    etc. — an Attribute call named ``publish`` whose owner is (or ends
    in an attribute named) one of _PUBLISH_OWNERS."""
    f = node.func
    if not isinstance(f, ast.Attribute) or f.attr != "publish":
        return False
    owner = f.value
    name = getattr(owner, "attr", getattr(owner, "id", None))
    return name in _PUBLISH_OWNERS


def lint_publish_spans(root: str) -> List[str]:
    """ISSUE 6 rule: every function under antidote_tpu/interdc/ with a
    ``transport.publish`` / ``bus.publish`` call site must also carry a
    span/instant/annotation, so outbound wire sends stay visible to the
    forensic plane even as they move between threads."""
    problems: List[str] = []
    d = os.path.join(root, _PUBLISH_DIR)
    if not os.path.isdir(d):
        return problems
    for fname in sorted(os.listdir(d)):
        if not fname.endswith(".py"):
            continue
        path = os.path.join(d, fname)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            has_publish = any(
                isinstance(c, ast.Call) and _is_publish_call(c)
                for c in ast.walk(node))
            if has_publish and not _is_instrumented(node):
                problems.append(
                    f"{_PUBLISH_DIR}/{fname}::{node.name}: "
                    "transport.publish call site without a tracer "
                    "span/instant — outbound frames go dark "
                    "(antidote_tpu/obs/spans.py)")
    return problems


def _is_decode_call(node: ast.Call) -> bool:
    """True for ``frame_from_bin(...)`` / ``wire.frame_from_bin(...)``
    / ``InterDcTxn.from_bin(...)`` — any call whose terminal name is a
    wire-frame decoder."""
    f = node.func
    name = getattr(f, "attr", getattr(f, "id", None))
    return name in _DECODE_NAMES


def lint_decode_instants(root: str) -> List[str]:
    """ISSUE 7 rule: every function under the interdc/cluster packages
    that decodes a wire frame must record the arrival instant with a
    tracer span/instant — arrival-side time is half of every
    visibility-latency measurement.  Functions NAMED like a decoder
    (the wire.py definitions) are exempt; call sites are not."""
    problems: List[str] = []
    for rel_dir in _DECODE_DIRS:
        d = os.path.join(root, rel_dir)
        if not os.path.isdir(d):
            continue
        for fname in sorted(os.listdir(d)):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(d, fname)
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if node.name in _DECODE_NAMES:
                    continue  # the decoder itself, not an arrival site
                decodes = any(
                    isinstance(c, ast.Call) and _is_decode_call(c)
                    for c in ast.walk(node))
                if decodes and not _is_instrumented(node):
                    problems.append(
                        f"{rel_dir}/{fname}::{node.name}: decodes a "
                        "wire frame without recording the arrival "
                        "instant — add tracer.instant/span (the "
                        "visibility SLOs need arrival-side time, "
                        "antidote_tpu/obs/spans.py)")
    return problems


def _is_fused_call(node: ast.Call) -> bool:
    """True for ``fused_read(...)`` / ``device_plane.fused_read(...)``
    — any call whose terminal name is a gathered-fold entry point."""
    f = node.func
    name = getattr(f, "attr", getattr(f, "id", None))
    return name in _FUSED_NAMES


def lint_fused_spans(root: str) -> List[str]:
    """ISSUE 8 rule: every function under antidote_tpu/mat/ that
    dispatches a gathered ``fused_read`` fold must carry a tracer
    span/instant — the serve plane's one-dispatch folds are the read
    path's hottest kernels and must stay on the serve-stage timeline.
    Functions NAMED like the fold (the device_plane definition) are
    exempt; call sites are not."""
    problems: List[str] = []
    for rel_dir in _FUSED_DIRS:
        d = os.path.join(root, rel_dir)
        if not os.path.isdir(d):
            continue
        for fname in sorted(os.listdir(d)):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(d, fname)
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if node.name in _FUSED_NAMES:
                    continue  # the fold itself, not a dispatch site
                fuses = any(
                    isinstance(c, ast.Call) and _is_fused_call(c)
                    for c in ast.walk(node))
                if fuses and not _is_instrumented(node):
                    problems.append(
                        f"{rel_dir}/{fname}::{node.name}: dispatches "
                        "a gathered fused_read fold without a tracer "
                        "span/instant — the serve-stage latency "
                        "panels go dark (antidote_tpu/obs/spans.py)")
    return problems


def _is_sync_call(node: ast.Call) -> bool:
    """True for ``self.log.sync()`` / ``os.fsync(fd)`` /
    ``lib.oplog_sync(h)`` — any call whose terminal name is a
    durability barrier."""
    f = node.func
    name = getattr(f, "attr", getattr(f, "id", None))
    return name in _SYNC_NAMES


def lint_sync_spans(root: str) -> List[str]:
    """ISSUE 9 rule: every function under antidote_tpu/oplog/ with an
    fsync/sync call site must also carry a span/instant/annotation, so
    the durability barrier stays visible to the forensic plane as the
    group-commit plane moves it between threads.  Functions named
    ``sync`` (the DurableLog/_PyLog barrier definitions) are exempt;
    call sites are not."""
    problems: List[str] = []
    d = os.path.join(root, _SYNC_DIR)
    if not os.path.isdir(d):
        return problems
    for fname in sorted(os.listdir(d)):
        if not fname.endswith(".py"):
            continue
        path = os.path.join(d, fname)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name in _SYNC_NAMES:
                continue  # the barrier definition, not a call site
            syncs = any(
                isinstance(c, ast.Call) and _is_sync_call(c)
                for c in ast.walk(node))
            if syncs and not _is_instrumented(node):
                problems.append(
                    f"{_SYNC_DIR}/{fname}::{node.name}: calls the "
                    "durability barrier (sync/fsync) without a tracer "
                    "span/instant — commit-path disk stalls go dark "
                    "(antidote_tpu/obs/spans.py)")
    return problems


def _is_ckpt_io_call(node: ast.Call) -> bool:
    """True for ``self.ckpt.write_doc(...)`` / ``store.load_doc(...)``
    / ``self.log.truncate_below(...)`` — any call whose terminal name
    is a checkpoint-IO primitive."""
    f = node.func
    name = getattr(f, "attr", getattr(f, "id", None))
    return name in _CKPT_IO_NAMES


def lint_ckpt_spans(root: str) -> List[str]:
    """ISSUE 10 rule: every function under antidote_tpu/oplog/ with a
    checkpoint-IO call site (write_doc / load_doc / truncate_below)
    must also carry a span/instant/annotation — checkpoint writes,
    recovery loads, and log truncations are the cold-path disk moves
    the CKPT_* forensics attribute stalls to.  Functions named like
    the IO primitives are the definitions themselves and exempt."""
    problems: List[str] = []
    d = os.path.join(root, _CKPT_DIR)
    if not os.path.isdir(d):
        return problems
    for fname in sorted(os.listdir(d)):
        if not fname.endswith(".py"):
            continue
        path = os.path.join(d, fname)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name in _CKPT_IO_NAMES:
                continue  # the IO definition, not a call site
            does_io = any(
                isinstance(c, ast.Call) and _is_ckpt_io_call(c)
                for c in ast.walk(node))
            if does_io and not _is_instrumented(node):
                problems.append(
                    f"{_CKPT_DIR}/{fname}::{node.name}: performs "
                    "checkpoint IO (write_doc/load_doc/truncate_below) "
                    "without a tracer span/instant — checkpoint and "
                    "truncation stalls go dark "
                    "(antidote_tpu/obs/spans.py)")
    return problems


def _methods(tree: ast.Module, cls_name: str) -> Dict[str, ast.FunctionDef]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            return {n.name: n for n in node.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
    return {}


def lint(root: str) -> List[str]:
    """All violations, as ``path::Class.method: <reason>`` strings."""
    problems: List[str] = []
    for rel, classes in sorted(ENTRY_POINTS.items()):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            problems.append(f"{rel}: file vanished (update ENTRY_POINTS "
                            "if the plane moved)")
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        for cls, methods in sorted(classes.items()):
            found = _methods(tree, cls)
            for m in methods:
                fn = found.get(m)
                if fn is None:
                    problems.append(
                        f"{rel}::{cls}.{m}: entry point missing "
                        "(renamed? update ENTRY_POINTS)")
                elif not _is_instrumented(fn):
                    problems.append(
                        f"{rel}::{cls}.{m}: no span/annotation — add "
                        "tracer.span/instant, prof.annotate, or "
                        "@traced")
    problems.extend(lint_kernel_spans(root))
    problems.extend(lint_publish_spans(root))
    problems.extend(lint_decode_instants(root))
    problems.extend(lint_fused_spans(root))
    problems.extend(lint_sync_spans(root))
    problems.extend(lint_ckpt_spans(root))
    return problems


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else repo_root()
    problems = lint(root)
    n_points = sum(len(ms) for classes in ENTRY_POINTS.values()
                   for ms in classes.values())
    if problems:
        print(f"trace_lint: {len(problems)} uninstrumented entry "
              f"point(s) of {n_points}:", file=sys.stderr)
        for p in problems:
            print(f"  {p}", file=sys.stderr)
        return 1
    print(f"trace_lint: OK — {n_points} entry points instrumented")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

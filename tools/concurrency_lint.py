#!/usr/bin/env python
"""concurrency_lint — the concurrency-discipline analyzer (ISSUE 11).

The bugs that cost review rounds in PRs 8-9 were not hygiene slips but
*concurrency-discipline* violations: an fsync or pickle under the
partition lock (found twice by human review), a plane constructed
outside its ``*_from_config`` factory (the gate_from_config lesson,
re-learned three times), and lock-order folklore distributed across
docstrings.  Cure's guarantees only hold if these invariants hold;
this lint encodes them as a static pass, the correctness-tooling
analogue of what trace_lint did for observability.  Three rule
families, all pure-ast (no imports of the package, runs in
milliseconds, needs no JAX):

**blocking-under-lock** [lock-blocking]: reconstruct lock-held regions
from ``with <lock>:`` blocks (a lock is any context expression whose
terminal name contains ``lock``, plus the per-module declaration table
``_DECLARED_LOCKS`` for condition variables named otherwise) and flag
calls that can block or burn unbounded time inside them: fsync/
fdatasync, the ``sync``/``oplog_sync`` durability barriers,
``pickle.dumps``/``loads``, ``os.replace``, ``time.sleep``,
``Condition.wait``/``Event.wait`` (waiting on the *held* condition is
the normal release-and-sleep idiom and exempt; waiting on any OTHER
object while holding a lock is the hazard), socket/transport sends,
device folds (``fused_read``, ``block_until_ready``,
``copy_to_host``), and this repo's own blocking primitives
(``wait_durable``, ``truncate_below``/``stage_truncate_below``,
``write_doc``/``load_doc``, ``checkpoint_now``).  The check
propagates through the intra-package call graph (a call under a lock
to a function that transitively blocks is the same bug with a stack
frame of indirection — exactly how the PR-8 fsync hid), resolving
``self.m()`` within the class and otherwise only names defined exactly
once in the package (ambiguity never invents a finding).  An inline
``# lock-ok: <reason>`` on the call line suppresses it, so every
surviving site is an *audited* decision; a ``# lock-ok`` without a
reason is itself a finding [lock-ok-reason] (the audit trail is the
point).

**lock-order** [lock-order]: extract nested acquisitions per function,
propagate acquisition sets through the same call graph, build the
global acquisition-order graph over lock identities
(``Class.attr`` / ``module:name``), and fail on cycles with the
witness edges.  Today the partition-lock -> log-handle-lock ->
``_pub_lock`` ordering is folklore; here it is a checked invariant.
Re-acquiring the SAME non-reentrant lock in one function (identical
``with`` expressions nested) is reported as a self-deadlock; self
edges that only arise through calls are ignored (two instances of the
same class are different locks).

**GIL policy** [gil-policy]: the native fabrics bind ONE shared
library twice, split by GIL policy (cluster/nativelink.py's ``_Lib``,
interdc/tcp.py's ``_FabLib``): blocking entry points — the condition
waits (``nl_wait``, ``nl_recv_batch``, ``nl_collect``), the
socket-binding/teardown class (``nl_create``, ``nl_shutdown``,
``fab_create``, ``fab_close``) and ``fab_publish`` (contends the hub
mutex against an event thread mid-send) — must bind via ``CDLL`` (GIL
released) and must never be CALLED inside a ``with <lock>:`` region
(a GIL-releasing call under a lock hands the lock's whole wait chain
to the scheduler); quick bookkeeping entry points must bind via
``PyDLL`` (a CDLL call re-acquires the GIL on return, costing up to a
scheduler timeslice against busy threads — measured at 4.4 ms per
start_request before the split).  The two tables below ARE the
policy: an entry point in neither is itself a finding, so a new
binding must be classified before it ships.  Keyed by the ASSIGNED
attribute name — ``self.nl_wait_probe = quick.nl_wait`` is the
deliberate zero-timeout GIL-held probe binding, a distinct entry
point with its own policy.

**collective launch discipline** [collective-lock]: runtime.py's
``COLLECTIVE_LOCK`` invariant — every multi-chip program launch must
hold the lock, or two threads' collectives interleave their ICI
programs and abort inside the XLA runtime — machine-enforced (ISSUE
20).  A name bound from a collective *builder* (``self._sm(...)``,
``shard_map_compat(...)``, possibly wrapped in ``jax.jit``/profiler
wrappers) is a launcher; calling it anywhere outside a ``with``
region whose items include ``COLLECTIVE_LOCK`` (either spelling),
``collective_guard(...)`` or ``_collective_cm()`` is a finding.  The
``lax.pmin/pmax/psum`` calls INSIDE a shard_map body are exempt by
construction — nested defs run at launch time, under the launcher's
lock, not at definition time.  ``# lock-ok: <reason>`` audits the
exceptions, same trail as [lock-blocking].

**knob routing + coverage** [knob-*]: direct construction of a
config-routed plane class (``_FACTORY_ROUTED``) anywhere in the
package outside its blessed factory module is an error — the
gate_from_config lesson, machine-enforced (benches and tests
deliberately construct baseline/variant assemblies and are not swept).
Additionally every ``config.<knob>`` / ``self.config.<knob>`` read in
the package must exist on :class:`antidote_tpu.config.Config`
[knob-unknown], and every declared knob must be read somewhere in
antidote_tpu/, benches/, tools/ or bench.py [knob-dead] — a knob
nothing reads is a promise the system does not keep.

Runs standalone (``python tools/concurrency_lint.py [root]``) and as
part of ``python -m tools.static_suite``; exit 0 = clean.  Fixture
tests: tests/unit/test_concurrency_lint.py.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import astcommon  # noqa: E402 — shared call-graph + suppression infra

#: package swept for lock discipline and knob routing (tests and
#: benches intentionally build variant assemblies and hold the GIL in
#: single-threaded harnesses — they are exempt by design)
PACKAGE_DIR = "antidote_tpu"

#: extra dirs whose Config reads count for dead-knob coverage
KNOB_READ_DIRS = ("antidote_tpu", "benches", "tools")
KNOB_READ_FILES = ("bench.py",)

#: attribute/variable names that hold a lock although their name does
#: not contain "lock" — the per-module declaration table.  Grow this
#: when a module names a condition variable something new; the lint
#: cannot guess that ``_cv`` sleeps.
_DECLARED_LOCKS: Dict[str, Set[str]] = {
    "antidote_tpu/txn/node.py": {"_cond"},
    "antidote_tpu/mat/serve.py": {"_cond"},
    "antidote_tpu/interdc/sender.py": {"_cv"},
    "antidote_tpu/cluster/nativelink.py": {"_inflight_cv"},
    "antidote_tpu/interdc/tcp.py": {"_hub_cv"},
}

#: config-routed plane classes -> modules blessed to construct them
#: (the defining module is always blessed; listed here are the factory
#: homes).  Direct construction anywhere else in the package bypasses
#: the ``*_from_config`` routing and is an error.
_FACTORY_ROUTED: Dict[str, Tuple[str, ...]] = {
    # settings dataclasses: the *_from_config factories live in the
    # defining modules; nothing else may invent defaults
    "GroupSettings": ("antidote_tpu/oplog/log.py",),
    "CheckpointSettings": ("antidote_tpu/oplog/checkpoint.py",),
    "IngestSettings": ("antidote_tpu/mat/ingest.py",),
    "ServeSettings": ("antidote_tpu/mat/serve.py",),
    # plane classes: Node's partition factory is the one assembly path
    "DependencyGate": ("antidote_tpu/interdc/dep.py",),
    "CheckpointStore": ("antidote_tpu/oplog/checkpoint.py",
                        "antidote_tpu/txn/node.py"),
    "ReadServer": ("antidote_tpu/mat/serve.py",
                   "antidote_tpu/txn/node.py"),
    "DevicePlane": ("antidote_tpu/mat/device_plane.py",
                    "antidote_tpu/txn/node.py"),
    # fabric endpoints (ISSUE 12): Config.fabric_native routes them —
    # build_link and transport_from_config are the construction paths
    "NativeNodeLink": ("antidote_tpu/cluster/nativelink.py",
                       "antidote_tpu/cluster/node.py"),
    "TcpTransport": ("antidote_tpu/interdc/tcp.py",),
}

#: builtin-type method shadowing table — factored to astcommon (ISSUE
#: 15) so durability_lint's call resolution cannot drift from ours
_NO_RESOLVE = astcommon.NO_RESOLVE

#: owners whose ``publish`` is the inter-DC pub/sub wire send (the
#: trace_lint _PUBLISH_OWNERS contract); a meta entry's monotone
#: ``e.publish`` is host arithmetic, not a socket
_PUBLISH_OWNERS = ("transport", "bus")

#: terminal call names that ALWAYS block (or burn unbounded time)
_BLOCKING_ALWAYS = {
    "fsync": "fsync",
    "fdatasync": "fsync",
    "sync": "durability barrier",
    "oplog_sync": "durability barrier",
    "sendall": "socket send",
    "send_frame": "transport send",
    "fused_read": "device fold",
    "block_until_ready": "device fold",
    "copy_to_host": "device fold",
    # this repo's own blocking primitives: machine-enforces their
    # documented "must not hold the partition lock" contracts
    "wait_durable": "durability wait",
    "truncate_below": "log-suffix rewrite",
    "stage_truncate_below": "log-suffix rewrite",
    "stage_truncation": "log-suffix rewrite",
    "write_doc": "checkpoint write (pickle + fsync)",
    "load_doc": "checkpoint load",
    "checkpoint_now": "checkpoint cut+fold+persist",
    # streamed segment transfer (ISSUE 19): manifest/segment reads,
    # durable staging, and the staged-resize install are all file IO
    # (often fsync-bearing) and must never run under a partition lock
    "_load_segment": "segment read",
    "bundle_manifest": "manifest read",
    "read_segment_raw": "segment read",
    "ship_bundle": "bundle read",
    "install_bundle": "bundle install (write + fsync)",
    "stage_resize_checkpoint": "resize-checkpoint stage (fsync)",
    "commit_staged_resize_checkpoint": "resize-checkpoint install",
    "offer": "segment stage (write + fsync)",
    "commit": "bundle/txn commit",
}

#: terminal names that block only with a specific owner
_BLOCKING_OWNED = {
    ("pickle", "dumps"): "pickle under a lock",
    ("pickle", "loads"): "pickle under a lock",
    ("pickle", "dump"): "pickle under a lock",
    ("pickle", "load"): "pickle under a lock",
    ("os", "replace"): "atomic rename",
    ("time", "sleep"): "sleep",
    ("transport", "publish"): "transport publish",
    ("bus", "publish"): "transport publish",
}

#: Condition/Event wait verbs (exempt when waiting on the held lock)
_WAIT_NAMES = {"wait", "wait_for"}

#: collective-program builders: a name assigned from a call reaching
#: one of these is a multi-chip launcher and must only be CALLED under
#: a collective region ([collective-lock], runtime.py's invariant)
_COLLECTIVE_BUILDERS = {"_sm", "shard_map_compat"}

#: with-item terminal names that satisfy the collective-launch
#: discipline: the lock itself (either import spelling), the
#: device_plane guard helper, and the per-plane context manager
_COLLECTIVE_REGIONS = {"COLLECTIVE_LOCK", "_COLLECTIVE_LOCK",
                       "collective_guard", "_collective_cm"}

#: native fabric entry points that BLOCK (condition waits, socket
#: bind/teardown, mutex contention against event threads): must bind
#: via ctypes.CDLL — the GIL is released for the call — and must never
#: be called inside a lock region (module docstring, [gil-policy]).
#: Keyed by the ASSIGNED attribute name, so the deliberate GIL-held
#: probe rebindings (nl_wait_probe = quick.nl_wait) classify
#: separately.
_GIL_BLOCKING = {
    "nl_create": "socket bind",
    "nl_wait": "reply condition wait",
    "nl_recv_batch": "inbound-request condition wait",
    "nl_collect": "fan-out collect wait",
    "nl_shutdown": "event-thread join",
    "fab_create": "socket bind",
    "fab_publish": "hub-mutex send contention",
    "fab_sub_count": "hub-mutex contention against the event "
                     "thread's send sweep",
    "fab_queued_bytes": "hub-mutex contention against the event "
                        "thread's send sweep",
    "fab_close": "event-thread join",
    # telemetry drains (ISSUE 16): bulk memcpy of up to 128 KiB out of
    # the flight-recorder ring — long enough to CDLL, and never wanted
    # inside a lock region anyway (they ride gauge/gossip cadences)
    "nl_tel_drain": "telemetry ring bulk copy",
    "fab_tel_drain": "telemetry ring bulk copy",
}

#: native fabric entry points that only do bookkeeping under the
#: endpoint mutex (whose holders never block): must bind via
#: ctypes.PyDLL — a CDLL call would pay a GIL re-acquisition (up to a
#: scheduler timeslice against busy threads) for microseconds of C
_GIL_QUICK = {
    "nl_port", "nl_set_peer", "nl_send", "nl_cancel", "nl_drop_peer",
    "nl_reply", "nl_free", "nl_publish", "nl_publish_clear",
    "nl_counters", "nl_pub_gen", "nl_wait_probe", "nl_collect_probe",
    "fab_port",
    # telemetry cursor/enable (ISSUE 16): atomics-only — no mutex, no
    # syscall; the watchdog probes them from Python-held paths
    "nl_tel_cursor", "nl_tel_enable", "fab_tel_cursor",
    "fab_tel_enable",
}


#: call-name extraction — shared with durability_lint (astcommon)
_terminal = astcommon.terminal

#: one parsed module + its ``# lock-ok`` suppressions (tokenize-based
#: COMMENT scan, comment-only lines attach to the next code line —
#: see astcommon.FileInfo, factored out for durability_lint's dur-ok)
_FileInfo = astcommon.FileInfo


def _expr_key(node: ast.expr) -> str:
    """Stable identity of a lock expression (``self._lock`` ==
    ``self._lock``) — ast.dump is deterministic for our purposes."""
    return ast.dump(node)


class _Func:
    """One function's concurrency facts."""

    def __init__(self, rel: str, cls: Optional[str], node):
        self.rel = rel
        self.cls = cls
        self.node = node
        self.name = node.name
        #: lock ids acquired directly (with-statements)
        self.direct_locks: Set[str] = set()
        #: (held_tuple, lock_id, lineno) per acquisition, for nesting
        #: edges and self-deadlock detection
        self.acquisitions: List[Tuple[Tuple[str, ...], str, int,
                                      str]] = []
        #: direct blocking facts: (kind, what, lineno, wait_lock_id)
        #: wait_lock_id is the waited-on lock for wait verbs (None for
        #: unconditional blockers) — the caller-side exemption key
        self.blocking: List[Tuple[str, str, int, Optional[str]]] = []
        #: call sites: (callee_name, owner_name, lineno, held_tuple)
        self.calls: List[Tuple[str, Optional[str], int,
                               Tuple[str, ...]]] = []

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


class _Analyzer:
    def __init__(self, root: str):
        self.root = root
        self.files: Dict[str, _FileInfo] = {}
        self.funcs: List[_Func] = []
        #: name/class call-resolution indices (astcommon.CallIndex)
        self.calls = astcommon.CallIndex()
        #: lock attr -> classes assigning it (owner-type heuristic)
        self.attr_owners: Dict[str, Set[str]] = {}
        #: (class, cv_attr) -> lock_attr for condition variables built
        #: AROUND an existing lock (``self._cv =
        #: threading.Condition(self._lock)`` shares the lock — waiting
        #: on the cv while holding the lock is the release-and-sleep
        #: idiom, not a second lock)
        self.cond_alias: Dict[Tuple[str, str], str] = {}
        #: (owning class or None, attr/name) -> "Lock"|"RLock"|
        #: "Condition"|"Event".  Keyed by CLASS, not bare attr:
        #: ``_lock`` is a Lock in one class and an RLock in another,
        #: and a first-hit attr lookup would misclassify every other
        #: class's lock.
        self.lock_kinds: Dict[Tuple[Optional[str], str], str] = {}

    # ------------------------------------------------------------ parse

    def load(self) -> List[str]:
        self.files, problems = astcommon.load_package(
            self.root, PACKAGE_DIR, marker="lock-ok")
        # pass 1: class metadata (lock attrs, Condition aliases) from
        # EVERY file — the function scan below resolves lock identity
        # across modules, so it must see the whole package's metadata
        for rel in sorted(self.files):
            self._collect_meta(self.files[rel])
        # pass 2: per-function concurrency facts
        for rel in sorted(self.files):
            self._collect_funcs(self.files[rel])
        for fn in self.funcs:
            self.calls.add(fn)
        return problems

    def _collect_funcs(self, info: _FileInfo) -> None:
        def walk(node, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    fn = _Func(info.rel, cls, child)
                    self.funcs.append(fn)
                    self._scan_func(info, fn)
                    walk(child, cls)  # nested defs: own lock scope
                else:
                    walk(child, cls)

        walk(info.tree, None)

    def _collect_meta(self, info: _FileInfo) -> None:
        """ONE scan per lock-object assignment records every fact the
        analyzer keeps about it: the owning class (obj.attr identity
        resolution), the kind (Lock/RLock/... — self-deadlock
        reporting skips reentrant locks), and Condition-around-a-lock
        aliases.  A single traversal on purpose: a new lock flavor
        added to one table but missed by another would make kind and
        owner resolution silently disagree."""

        def scan(body, cls):
            for node in body:
                if isinstance(node, ast.ClassDef):
                    scan(node.body, node.name)
                    continue
                for sub in ast.walk(node):
                    if not (isinstance(sub, ast.Assign)
                            and isinstance(sub.value, ast.Call)):
                        continue
                    kind = _terminal(sub.value.func)
                    if kind not in ("Lock", "RLock", "Condition",
                                    "Event"):
                        continue
                    inner = _terminal(sub.value.args[0]) \
                        if kind == "Condition" and sub.value.args \
                        else None
                    for t in sub.targets:
                        name = _terminal(t)
                        if not name:
                            continue
                        self.lock_kinds[(cls, name)] = kind
                        if cls:
                            self.attr_owners.setdefault(
                                name, set()).add(cls)
                            if inner:
                                self.cond_alias[(cls, name)] = inner

        scan(info.tree.body, None)

    # --------------------------------------------------- lock identity

    def _is_lock_expr(self, info: _FileInfo, node: ast.expr) -> bool:
        name = _terminal(node)
        if name is None:
            return False
        declared = _DECLARED_LOCKS.get(info.rel, set())
        return "lock" in name.lower() or name in declared

    def _lock_id(self, info: _FileInfo, fn: _Func,
                 node: ast.expr) -> str:
        name = _terminal(node)
        if isinstance(node, ast.Attribute):
            owner = node.value
            if isinstance(owner, ast.Name) and owner.id == "self":
                cls = fn.cls or info.rel
                name = self.cond_alias.get((fn.cls, name), name) \
                    if fn.cls else name
                return f"{cls}.{name}"
            owners = self.attr_owners.get(name, set())
            if len(owners) == 1:
                cls = next(iter(owners))
                name = self.cond_alias.get((cls, name), name)
                return f"{cls}.{name}"
            return f"{_terminal(owner)}.{name}"
        return f"{info.rel}:{name}"

    def _lock_kind(self, lock_id: str) -> str:
        """Kind for a lock identity: exact (class, attr) declaration
        first; else the attr-wide consensus across the package; on a
        CONFLICT (same attr is Lock here, RLock there) answer RLock —
        ambiguity must never invent a self-deadlock finding."""
        if ":" in lock_id:
            cls, attr = None, lock_id.rsplit(":", 1)[-1]
        else:
            cls, attr = lock_id.rsplit(".", 1)
        if cls is not None and (cls, attr) in self.lock_kinds:
            return self.lock_kinds[(cls, attr)]
        kinds = {k for (c, a), k in self.lock_kinds.items()
                 if a == attr}
        if len(kinds) == 1:
            return kinds.pop()
        if kinds:
            return "RLock"
        return "Lock"

    # ----------------------------------------------------- per-function

    def _scan_func(self, info: _FileInfo, fn: _Func) -> None:
        """Walk one function body tracking the with-lock stack; nested
        defs are skipped (their body runs at call time, not under this
        region — they are scanned as their own functions)."""

        def classify(call: ast.Call
                     ) -> Optional[Tuple[str, str, Optional[str]]]:
            f = call.func
            name = _terminal(f)
            if name is None:
                return None
            owner = _terminal(f.value) if isinstance(
                f, ast.Attribute) else None
            if name in _WAIT_NAMES and isinstance(f, ast.Attribute):
                wl = self._lock_id(info, fn, f.value) \
                    if self._is_lock_expr(info, f.value) else \
                    f"{owner}.{name}"
                return ("wait", f"{owner}.{name}", wl)
            if name in _GIL_BLOCKING and fn.name != name:
                # [gil-policy]: a GIL-releasing native call under a
                # lock hands the lock's whole wait chain to the
                # scheduler (and fab_publish can contend an event
                # thread mid-send for the send's duration)
                return ("gil", "GIL-releasing native call "
                               f"{name} ({_GIL_BLOCKING[name]})", None)
            if name in _BLOCKING_ALWAYS and fn.name != name:
                # a function NAMED like the primitive is its
                # definition/wrapper, not a call-under-lock site
                return ("blocking", _BLOCKING_ALWAYS[name], None)
            if owner is not None and (owner, name) in _BLOCKING_OWNED:
                return ("blocking", _BLOCKING_OWNED[(owner, name)],
                        None)
            return None

        def visit(node, held: Tuple[Tuple[str, str], ...]):
            # held: ((lock_id, expr_key), ...) outermost first
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    new_held = held
                    for item in child.items:
                        ctx = item.context_expr
                        if self._is_lock_expr(info, ctx):
                            lid = self._lock_id(info, fn, ctx)
                            fn.direct_locks.add(lid)
                            fn.acquisitions.append(
                                (tuple(h[0] for h in new_held), lid,
                                 child.lineno, _expr_key(ctx)))
                            new_held = new_held + (
                                (lid, _expr_key(ctx)),)
                    visit(child, new_held)
                    continue
                if isinstance(child, ast.Call):
                    cls = classify(child)
                    # a reasoned `# lock-ok` ON the blocking line
                    # audits it for every lock context — callers'
                    # propagated findings are covered by the one
                    # source-site audit (the legacy inline-fsync
                    # pattern: one audited line, five call sites)
                    if cls is not None and not info.suppress.get(
                            child.lineno):
                        kind, what, wl = cls
                        fn.blocking.append(
                            (kind, what, child.lineno, wl))
                    name = _terminal(child.func)
                    owner = _terminal(child.func.value) if isinstance(
                        child.func, ast.Attribute) else None
                    if name:
                        fn.calls.append(
                            (name, owner, child.lineno,
                             tuple(h[0] for h in held)))
                visit(child, held)

        visit(fn.node, ())
        # waits on a lock the region holds are the release-and-sleep
        # idiom: drop them from the blocking set entirely when the
        # waited lock is held at the site (re-derived here with the
        # held stack per line)
        held_at: Dict[int, Set[str]] = {}
        self._held_lines(fn.node, info, fn, (), held_at)
        fn.blocking = [
            (k, w, ln, wl) for (k, w, ln, wl) in fn.blocking
            if not (k == "wait" and wl in held_at.get(ln, set()))]

    def _held_lines(self, node, info, fn, held, out) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            new_held = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    ctx = item.context_expr
                    if self._is_lock_expr(info, ctx):
                        new_held = new_held + (
                            self._lock_id(info, fn, ctx),)
            for n in ast.walk(child):
                ln = getattr(n, "lineno", None)
                if ln is not None:
                    out.setdefault(ln, set()).update(new_held)
            if isinstance(child, (ast.With, ast.AsyncWith)):
                self._held_lines(child, info, fn, new_held, out)
            else:
                self._held_lines(child, info, fn, held, out)

    # ------------------------------------------------- call resolution

    def resolve(self, caller: _Func, name: str,
                owner: Optional[str]) -> Optional[_Func]:
        return self.calls.resolve(caller.cls, name, owner)

    # ------------------------------------------ transitive blocking set

    def _transitive_blocking(self) -> Dict[
            _Func, List[Tuple[str, str, Optional[str], str]]]:
        """func -> [(kind, what, wait_lock, via)]: every blocking fact
        reachable from it through resolvable calls, with the access
        path ("a -> b -> fsync") for the finding message."""
        memo: Dict[_Func, List] = {}

        def go(fn: _Func, stack: Set[_Func]):
            if fn in memo:
                return memo[fn]
            if fn in stack:
                return []
            memo[fn] = out = [
                (k, w, wl, f"{fn.qual}:{ln}")
                for (k, w, ln, wl) in fn.blocking]
            stack.add(fn)
            for (name, owner, _ln, _held) in fn.calls:
                callee = self.resolve(fn, name, owner)
                if callee is None or callee is fn:
                    continue
                for (k, w, wl, via) in go(callee, stack):
                    out.append((k, w, wl, f"{fn.qual} -> {via}"))
            stack.discard(fn)
            # dedupe by (kind, what, wait lock): one witness is enough
            seen: Set[Tuple] = set()
            uniq = []
            for item in out:
                key = item[:3]
                if key not in seen:
                    seen.add(key)
                    uniq.append(item)
            memo[fn] = uniq
            return uniq

        for fn in self.funcs:
            go(fn, set())
        return memo

    # ------------------------------------------------ rule 1: blocking

    def lint_blocking(self) -> List[str]:
        problems: List[str] = []
        trans = self._transitive_blocking()
        for fn in self.funcs:
            info = self.files[fn.rel]
            # direct blocking calls inside a lock region
            held_at: Dict[int, Set[str]] = {}
            self._held_lines(fn.node, info, fn, (), held_at)
            for (kind, what, ln, wl) in fn.blocking:
                held = held_at.get(ln, set())
                if not held:
                    continue
                if kind == "wait" and wl in held:
                    continue
                if self._suppressed(info, ln):
                    continue
                tag = "gil-policy" if kind == "gil" else "lock-blocking"
                problems.append(
                    f"{fn.rel}:{ln}: [{tag}] {what} "
                    f"({fn.qual}) inside lock region "
                    f"{{{', '.join(sorted(held))}}} — move it out or "
                    "audit with `# lock-ok: <reason>`")
            # calls under a lock to transitively-blocking functions
            for (name, owner, ln, held) in fn.calls:
                if not held:
                    continue
                callee = self.resolve(fn, name, owner)
                if callee is None or callee is fn:
                    continue
                facts = trans.get(callee, [])
                hit = next(
                    (f for f in facts
                     if not (f[0] == "wait" and self._wait_covered(
                         f[2], held, owner))), None)
                if hit is None:
                    continue
                if self._suppressed(info, ln):
                    continue
                kind, what, _wl, via = hit
                tag = "gil-policy" if kind == "gil" else "lock-blocking"
                problems.append(
                    f"{fn.rel}:{ln}: [{tag}] call to "
                    f"{name}() under {{{', '.join(sorted(held))}}} "
                    f"reaches a {what} ({via}) — move it out or "
                    "audit with `# lock-ok: <reason>`")
        return problems

    @staticmethod
    def _wait_covered(wl: Optional[str], held,
                      call_owner: Optional[str]) -> bool:
        """True when a propagated wait fact sleeps on a lock the call
        site already holds.  Exact id match first; otherwise the
        untyped-owner form: holding ``pm._lock`` while calling
        ``pm._wait_x()`` whose wait is ``PartitionManager._lock`` is
        the same object — the callee's ``self`` IS the call owner, so
        matching attribute + matching owner name covers it."""
        if wl is None:
            return False
        if wl in held:
            return True
        attr = wl.rsplit(".", 1)[-1]
        for h in held:
            if "." in h and h.rsplit(".", 1)[-1] == attr \
                    and h.rsplit(".", 1)[0] == call_owner:
                return True
        return False

    def _suppressed(self, info: _FileInfo, lineno: int) -> bool:
        return info.suppressed(lineno)

    def lint_lock_ok_reasons(self) -> List[str]:
        """A ``# lock-ok`` with no reason defeats the audit trail the
        suppression exists to create — itself a finding."""
        problems = []
        for rel in sorted(self.files):
            for ln, reason in self.files[rel].suppress_sites:
                if not reason:
                    problems.append(
                        f"{rel}:{ln}: [lock-ok-reason] `# lock-ok` "
                        "without a reason — write `# lock-ok: <why "
                        "this blocking call must stay under the "
                        "lock>`")
        return problems

    # ---------------------------------------------- rule 2: lock order

    def _transitive_locks(self) -> Dict[_Func, Set[str]]:
        memo: Dict[_Func, Set[str]] = {}

        def go(fn: _Func, stack: Set[_Func]) -> Set[str]:
            if fn in memo:
                return memo[fn]
            if fn in stack:
                return set()
            stack.add(fn)
            out = set(fn.direct_locks)
            for (name, owner, _ln, _held) in fn.calls:
                callee = self.resolve(fn, name, owner)
                if callee is not None and callee is not fn:
                    out |= go(callee, stack)
            stack.discard(fn)
            memo[fn] = out
            return out

        for fn in self.funcs:
            go(fn, set())
        return memo

    def lint_lock_order(self) -> List[str]:
        problems: List[str] = []
        edges: Dict[Tuple[str, str], str] = {}
        # direct nesting (and same-expression re-acquire)
        for fn in self.funcs:
            info = self.files[fn.rel]
            seen_exprs: List[Tuple[Tuple[str, ...], str, int, str]] \
                = fn.acquisitions
            for (held, lid, ln, ekey) in seen_exprs:
                if self._suppressed(info, ln):
                    continue
                for h in held:
                    if h == lid:
                        continue  # self edge via re-entry: see below
                    edges.setdefault(
                        (h, lid),
                        f"{fn.rel}:{ln} ({fn.qual}: {h} -> {lid})")
            # identical-expression nested re-acquire of a
            # non-reentrant lock: a guaranteed self-deadlock
            for (held, lid, ln, ekey) in seen_exprs:
                if self._suppressed(info, ln):
                    continue
                # find an enclosing acquisition with the same expr
                for (held2, lid2, ln2, ekey2) in seen_exprs:
                    if (ln2 < ln and ekey2 == ekey and lid2 == lid
                            and lid in held
                            and self._lock_kind(lid) != "RLock"):
                        problems.append(
                            f"{fn.rel}:{ln}: [lock-order] {fn.qual} "
                            f"re-acquires non-reentrant {lid} it "
                            f"already holds (first taken at line "
                            f"{ln2}) — self-deadlock")
                        break
        # held-across-call edges
        trans = self._transitive_locks()
        for fn in self.funcs:
            info = self.files[fn.rel]
            for (name, owner, ln, held) in fn.calls:
                if not held:
                    continue
                callee = self.resolve(fn, name, owner)
                if callee is None or callee is fn:
                    continue
                if self._suppressed(info, ln):
                    continue
                for lid in trans.get(callee, ()):
                    for h in held:
                        if h != lid:
                            edges.setdefault(
                                (h, lid),
                                f"{fn.rel}:{ln} ({fn.qual} holds {h},"
                                f" {name}() acquires {lid})")
        problems.extend(self._find_cycles(edges))
        return problems

    @staticmethod
    def _find_cycles(edges: Dict[Tuple[str, str], str]) -> List[str]:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        problems = []
        color: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(u: str) -> Optional[List[str]]:
            color[u] = 1
            stack.append(u)
            for v in sorted(graph[u]):
                if color.get(v, 0) == 1:
                    return stack[stack.index(v):] + [v]
                if color.get(v, 0) == 0:
                    cyc = dfs(v)
                    if cyc:
                        return cyc
            stack.pop()
            color[u] = 2
            return None

        for u in sorted(graph):
            if color.get(u, 0) == 0:
                cyc = dfs(u)
                if cyc:
                    witness = []
                    for a, b in zip(cyc, cyc[1:]):
                        witness.append(f"  {a} -> {b}: "
                                       f"{edges[(a, b)]}")
                    problems.append(
                        "[lock-order] acquisition-order cycle "
                        + " -> ".join(cyc) + "\n"
                        + "\n".join(witness))
                    break  # one witness cycle is actionable enough
        return problems

    # ------------------------------------ rule: collective launch lock

    def lint_collective_lock(self) -> List[str]:
        """Calls of names bound from a collective builder
        (``self._sm(...)`` / ``shard_map_compat(...)``, possibly
        wrapped in ``jax.jit``/profiler wrap calls) must sit inside a
        ``with`` region whose items include COLLECTIVE_LOCK,
        ``collective_guard(...)`` or ``_collective_cm()`` — two
        threads' interleaved multi-chip programs abort inside the XLA
        runtime, so runtime.py makes the lock the law and this rule
        makes the law checkable.  Nested defs and lambdas (the
        shard_map BODIES, where ``lax.pmin/pmax/psum`` live) are
        skipped: they execute at launch time under the launcher's
        region, not at definition time."""
        problems: List[str] = []

        def region_item(ctx: ast.expr) -> bool:
            f = ctx.func if isinstance(ctx, ast.Call) else ctx
            return _terminal(f) in _COLLECTIVE_REGIONS

        for fn in self.funcs:
            info = self.files[fn.rel]
            launchers: Set[str] = set()

            def scan(node, covered: bool):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda)):
                        continue
                    if isinstance(child, (ast.With, ast.AsyncWith)):
                        scan(child, covered or any(
                            region_item(i.context_expr)
                            for i in child.items))
                        continue
                    if isinstance(child, ast.Assign) and any(
                            isinstance(n, ast.Call)
                            and _terminal(n.func)
                            in _COLLECTIVE_BUILDERS
                            for n in ast.walk(child.value)):
                        for t in child.targets:
                            name = _terminal(t)
                            if name:
                                launchers.add(name)
                    if isinstance(child, ast.Call):
                        name = _terminal(child.func)
                        if name in launchers and not covered \
                                and not self._suppressed(
                                    info, child.lineno):
                            problems.append(
                                f"{fn.rel}:{child.lineno}: "
                                f"[collective-lock] multi-chip "
                                f"program {name}() launched outside "
                                f"a COLLECTIVE_LOCK region "
                                f"({fn.qual}) — wrap the launch in "
                                "`with COLLECTIVE_LOCK:` / "
                                "`collective_guard(dev)` / "
                                "`self._collective_cm()` or audit "
                                "with `# lock-ok: <reason>`")
                    scan(child, covered)

            scan(fn.node, False)
        return problems

    # ----------------------------------------- rule: GIL binding policy

    def lint_gil_bindings(self) -> List[str]:
        """Every ``x.attr = <dll_var>.<sym>`` binding where <dll_var>
        was assigned from ``ctypes.CDLL(...)`` / ``ctypes.PyDLL(...)``
        must agree with the policy tables, keyed by the ASSIGNED
        attribute name (``nl_wait_probe = quick.nl_wait`` is the
        deliberate GIL-held probe, its own entry point).  A bound name
        in neither table is itself a finding — the tables ARE the
        policy, and an unclassified binding means nobody decided."""
        problems: List[str] = []
        for rel in sorted(self.files):
            info = self.files[rel]
            # dll handle vars per file: name -> "CDLL" | "PyDLL"
            dll_vars: Dict[str, str] = {}
            for node in ast.walk(info.tree):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                kind = _terminal(node.value.func)
                if kind not in ("CDLL", "PyDLL"):
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        dll_vars[t.id] = kind
            if not dll_vars:
                continue
            for node in ast.walk(info.tree):
                if not isinstance(node, ast.Assign):
                    continue
                v = node.value
                if not (isinstance(v, ast.Attribute)
                        and isinstance(v.value, ast.Name)
                        and v.value.id in dll_vars):
                    continue
                policy = dll_vars[v.value.id]
                for t in node.targets:
                    bound = _terminal(t)
                    if bound is None:
                        continue
                    if self._suppressed(info, node.lineno):
                        continue
                    if bound in _GIL_BLOCKING:
                        if policy != "CDLL":
                            problems.append(
                                f"{rel}:{node.lineno}: [gil-policy] "
                                f"blocking native entry point {bound} "
                                f"({_GIL_BLOCKING[bound]}) bound via "
                                "PyDLL — it holds the GIL across a "
                                "blocking call; bind via CDLL")
                    elif bound in _GIL_QUICK:
                        if policy != "PyDLL":
                            problems.append(
                                f"{rel}:{node.lineno}: [gil-policy] "
                                f"quick native entry point {bound} "
                                "bound via CDLL — the GIL "
                                "re-acquisition on return costs up to "
                                "a scheduler timeslice per call; bind "
                                "via PyDLL")
                    else:
                        problems.append(
                            f"{rel}:{node.lineno}: [gil-policy] "
                            f"unclassified native entry point {bound} "
                            "bound from a ctypes library — add it to "
                            "_GIL_BLOCKING or _GIL_QUICK (the tables "
                            "are the policy)")
        return problems

    # -------------------------------------- rule 3: knob routing + cov

    def lint_knobs(self) -> List[str]:
        problems: List[str] = []
        # construction routing
        for fn_rel in sorted(self.files):
            info = self.files[fn_rel]
            for node in ast.walk(info.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = _terminal(node.func)
                blessed = _FACTORY_ROUTED.get(name or "")
                if blessed is None:
                    continue
                if fn_rel.replace(os.sep, "/") in blessed:
                    continue
                if self._suppressed(info, node.lineno):
                    continue
                problems.append(
                    f"{fn_rel}:{node.lineno}: [knob-routing] direct "
                    f"{name}(...) construction outside its factory "
                    f"({', '.join(blessed)}) — route through the "
                    "*_from_config path (the gate_from_config "
                    "lesson)")
        # knob existence + dead knobs
        knobs = self._config_knobs()
        if knobs is None:
            problems.append(
                f"{PACKAGE_DIR}/config.py: [knob-unknown] Config "
                "class not found — knob coverage cannot run")
            return problems
        reads: Set[str] = set()
        for rel, tree in self._knob_read_trees():
            in_pkg = rel.startswith(PACKAGE_DIR)
            for node in ast.walk(tree):
                # version-tolerant reads spell the knob as a string:
                # getattr(config, "knob", default) — count them too,
                # and hold their names to the same existence bar (a
                # typo here is WORSE: the default hides it forever)
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id == "getattr" \
                        and len(node.args) >= 2 \
                        and self._is_config_owner(node.args[0]) \
                        and isinstance(node.args[1], ast.Constant) \
                        and isinstance(node.args[1].value, str):
                    attr = node.args[1].value
                elif isinstance(node, ast.Attribute) \
                        and self._is_config_owner(node.value):
                    attr = node.attr
                else:
                    continue
                reads.add(attr)
                if in_pkg and attr not in knobs \
                        and rel != f"{PACKAGE_DIR}/config.py":
                    problems.append(
                        f"{rel}:{node.lineno}: [knob-unknown] "
                        f"Config.{attr} is read but not declared "
                        "on Config — a typo here silently falls "
                        "through to defaults")
        for knob in sorted(knobs - reads):
            problems.append(
                f"{PACKAGE_DIR}/config.py: [knob-dead] Config."
                f"{knob} is declared but never read anywhere in "
                f"{', '.join(KNOB_READ_DIRS + KNOB_READ_FILES)} — "
                "route it or delete it")
        return problems

    def _config_knobs(self) -> Optional[Set[str]]:
        rel = f"{PACKAGE_DIR}/config.py"
        info = self.files.get(rel)
        if info is None:
            return None
        for node in ast.walk(info.tree):
            if isinstance(node, ast.ClassDef) and node.name == "Config":
                out = set()
                for st in node.body:
                    if isinstance(st, ast.AnnAssign) and isinstance(
                            st.target, ast.Name):
                        out.add(st.target.id)
                return out
        return None

    def _knob_read_trees(self):
        for d in KNOB_READ_DIRS:
            base = os.path.join(self.root, d)
            if not os.path.isdir(base):
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [x for x in dirnames
                               if x not in ("__pycache__", "_build")]
                for fname in sorted(filenames):
                    if not fname.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fname)
                    rel = os.path.relpath(path, self.root)
                    rel = rel.replace(os.sep, "/")
                    if rel in self.files:
                        yield rel, self.files[rel].tree
                        continue
                    try:
                        with open(path) as f:
                            yield rel, ast.parse(f.read())
                    except SyntaxError:
                        continue  # analysis_gate owns syntax findings
        for fname in KNOB_READ_FILES:
            path = os.path.join(self.root, fname)
            if os.path.exists(path):
                try:
                    with open(path) as f:
                        yield fname, ast.parse(f.read())
                except SyntaxError:
                    continue

    @staticmethod
    def _is_config_owner(owner: ast.expr) -> bool:
        """True when ``owner`` is a Config-holding expression:
        bare ``config``/``cfg`` or ``<obj>.config`` / ``<obj>.cfg`` /
        ``<obj>._config`` where <obj> is a plain name that is not a
        known foreign module (``jax.config.update`` is jax's)."""
        if isinstance(owner, ast.Name):
            return owner.id in ("config", "cfg")
        if isinstance(owner, ast.Attribute):
            if owner.attr not in ("config", "cfg", "_config"):
                return False
            root = owner.value
            while isinstance(root, ast.Attribute):
                root = root.value
            return isinstance(root, ast.Name) \
                and root.id not in ("jax", "_jax")
        return False


def lint(root: str) -> List[str]:
    an = _Analyzer(root)
    problems = an.load()
    problems.extend(an.lint_blocking())
    problems.extend(an.lint_lock_ok_reasons())
    problems.extend(an.lint_lock_order())
    problems.extend(an.lint_collective_lock())
    problems.extend(an.lint_gil_bindings())
    problems.extend(an.lint_knobs())
    return problems


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else repo_root()
    problems = lint(root)
    if problems:
        print(f"concurrency_lint: {len(problems)} finding(s):",
              file=sys.stderr)
        for p in problems:
            print(f"  {p}", file=sys.stderr)
        return 1
    print("concurrency_lint: OK — lock regions, acquisition order, "
          "and knob routing are clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

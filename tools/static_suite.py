"""static_suite — the one entry point for every static pass (ISSUE 11).

The reference wires dialyzer/elvis into ``make test`` as a single
stage; our analyzers grew one at a time (analysis_gate in PR 1,
trace_lint in PR 1-10, concurrency_lint in PR 11) and each needed its
own CI hook — a new rule that forgot its hook silently missed CI.
This module is the aggregation point:

    python -m tools.static_suite          # exit 0 = the repo is clean

runs, over the ONE shared path list (``SUITE_PATHS``):

- **analysis_gate** — surface hygiene: syntax, unused imports, bare
  except, mutable defaults, duplicate defs, literal compares
  (suppress with ``# noqa``)
- **trace_lint** — observability coverage: entry-point spans, kernel
  spans, publish/decode instants, sync/checkpoint IO spans
- **concurrency_lint** — concurrency discipline: blocking calls under
  a lock (suppress with ``# lock-ok: <reason>``), lock acquisition
  order, config-knob routing + coverage
- **durability_lint** — durability protocol (ISSUE 15): atomic
  publishes (fsync + rename + dir fsync), commit-point ordering
  (unlink only after the rename that obsoletes), immutable-file and
  torn-frame contracts, loud recovery (suppress with
  ``# dur-ok: <reason>``)
- **stats-dashboard** (lives here) — every metric family registered
  in antidote_tpu/stats.py must appear in the Grafana dashboard or
  monitoring/README.md: PR 5-9 each hand-maintained that mapping and
  a dark metric is a dashboard hole nobody notices until an incident
  [stats-dashboard]

tests/unit/test_static_suite.py runs :func:`run` repo-clean as the
single tier-1 gate, so an analyzer added to ``PASSES`` is gated from
the commit that adds it.  To add a pass: write ``lint(root) ->
[str]`` in a tools/ module, append ``(name, fn)`` to ``PASSES``, and
add a fixture test proving the rule fires.

``--json`` (ISSUE 15 satellite) emits the machine-readable form —
per-pass finding lists, counts, and wall-clock ms — so the CI log is
greppable and a slow pass is attributable:

    python -m tools.static_suite --json | jq '.passes[] | {name, ms}'
"""

from __future__ import annotations

import ast
import json
import os
import sys
import time
from typing import Callable, List, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import analysis_gate  # noqa: E402
import concurrency_lint  # noqa: E402
import durability_lint  # noqa: E402
import trace_lint  # noqa: E402

#: the one shared path list: everything the hygiene pass sweeps.  The
#: deeper passes (trace_lint / concurrency_lint) take the repo root
#: and restrict themselves to the package dirs they understand.
SUITE_PATHS = analysis_gate.DEFAULT_PATHS

#: metric-class constructors whose first argument is the family name
_METRIC_CLASSES = ("Counter", "Gauge", "LabeledGauge", "Histogram")

#: documentation surfaces a metric family must appear in (either)
_DASHBOARD_DOCS = (
    os.path.join("monitoring", "antidote-tpu-dashboard.json"),
    os.path.join("monitoring", "README.md"),
)


def _gate(root: str) -> List[str]:
    from pathlib import Path
    return [f"{path}:{line}: [{code}] {msg}"
            for path, line, code, msg
            in analysis_gate.run(SUITE_PATHS, root=Path(root))]


def lint_stats_dashboard(root: str) -> List[str]:
    """Every metric family name registered in antidote_tpu/stats.py
    must appear in the packaged Grafana dashboard or the monitoring
    README — a registered-but-undocumented family is invisible
    exactly when someone needs it (PR 5-9 hand-kept this mapping)."""
    stats_py = os.path.join(root, "antidote_tpu", "stats.py")
    if not os.path.exists(stats_py):
        return [f"antidote_tpu/stats.py: [stats-dashboard] missing — "
                "the metrics registry moved?"]
    with open(stats_py) as f:
        tree = ast.parse(f.read(), filename=stats_py)
    families: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and getattr(node.func, "id", None) in _METRIC_CLASSES
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            families.append((node.args[0].value, node.lineno))
    corpus = ""
    missing_docs = []
    for rel in _DASHBOARD_DOCS:
        path = os.path.join(root, rel)
        if os.path.exists(path):
            with open(path) as f:
                corpus += f.read()
        else:
            missing_docs.append(rel)
    if not corpus:
        return [f"{' / '.join(missing_docs)}: [stats-dashboard] no "
                "dashboard docs found — the monitoring/ surface moved?"]
    problems = []
    for name, lineno in sorted(families):
        if name not in corpus:
            problems.append(
                f"antidote_tpu/stats.py:{lineno}: [stats-dashboard] "
                f"metric family {name!r} is registered but appears in "
                f"neither {' nor '.join(_DASHBOARD_DOCS)} — add a "
                "panel or document it in the README")
    return problems


#: (name, lint) — every pass the suite runs; the tier-1 gate iterates
#: THIS list, so appending here is all a new analyzer needs for CI
PASSES: Tuple[Tuple[str, Callable[[str], List[str]]], ...] = (
    ("analysis_gate", _gate),
    ("trace_lint", trace_lint.lint),
    ("concurrency_lint", concurrency_lint.lint),
    ("durability_lint", durability_lint.lint),
    ("stats-dashboard", lint_stats_dashboard),
)


def run_timed(root: str | None = None) -> List[dict]:
    """Every pass with its findings, count and wall-clock ms — the
    machine-readable form ``--json`` emits, and what :func:`run`
    flattens.  Timing rides along so a slow pass in CI is attributable
    to its analyzer instead of 'the suite got slow'."""
    root = root or repo_root()
    out: List[dict] = []
    for name, fn in PASSES:
        t0 = time.perf_counter()
        findings = fn(root)
        out.append({
            "name": name,
            "findings": findings,
            "count": len(findings),
            "ms": round((time.perf_counter() - t0) * 1e3, 2),
        })
    return out


def run(root: str | None = None) -> List[str]:
    """Every pass's findings, prefixed with the pass name."""
    return [f"{p['name']}: {f}"
            for p in run_timed(root) for f in p["findings"]]


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    as_json = "--json" in argv
    rest = [a for a in argv if a != "--json"]
    root = rest[0] if rest else repo_root()
    if as_json:
        passes = run_timed(root)
        total = sum(p["count"] for p in passes)
        print(json.dumps({
            "ok": total == 0,
            "total_findings": total,
            "total_ms": round(sum(p["ms"] for p in passes), 2),
            "passes": passes,
        }, indent=2))
        return 1 if total else 0
    problems = run(root)
    if problems:
        print(f"static_suite: {len(problems)} finding(s) across "
              f"{len(PASSES)} passes:", file=sys.stderr)
        for p in problems:
            print(f"  {p}", file=sys.stderr)
        return 1
    print(f"static_suite: OK — {len(PASSES)} passes clean "
          f"({', '.join(n for n, _ in PASSES)})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""static_suite — the one entry point for every static pass (ISSUE 11).

The reference wires dialyzer/elvis into ``make test`` as a single
stage; our analyzers grew one at a time (analysis_gate in PR 1,
trace_lint in PR 1-10, concurrency_lint in PR 11) and each needed its
own CI hook — a new rule that forgot its hook silently missed CI.
This module is the aggregation point:

    python -m tools.static_suite          # exit 0 = the repo is clean

runs, over the ONE shared path list (``SUITE_PATHS``):

- **analysis_gate** — surface hygiene: syntax, unused imports, bare
  except, mutable defaults, duplicate defs, literal compares
  (suppress with ``# noqa``)
- **trace_lint** — observability coverage: entry-point spans, kernel
  spans, publish/decode instants, sync/checkpoint IO spans
- **concurrency_lint** — concurrency discipline: blocking calls under
  a lock (suppress with ``# lock-ok: <reason>``), lock acquisition
  order, config-knob routing + coverage
- **durability_lint** — durability protocol (ISSUE 15): atomic
  publishes (fsync + rename + dir fsync), commit-point ordering
  (unlink only after the rename that obsoletes), immutable-file and
  torn-frame contracts, loud recovery (suppress with
  ``# dur-ok: <reason>``)
- **stats-dashboard** (lives here) — every metric family registered
  in antidote_tpu/stats.py must appear in the Grafana dashboard or
  monitoring/README.md: PR 5-9 each hand-maintained that mapping and
  a dark metric is a dashboard hole nobody notices until an incident
  [stats-dashboard]
- **native-telemetry** (lives here, ISSUE 16) — every C++ flight-
  recorder event kind (``TEL_EV_*`` in native/tel_ring.h) must have a
  decode entry in obs/nativeobs.py, fold into at least one stats
  family that is actually registered, and that family must appear in
  the dashboard docs — a kind the C++ plane records but Python never
  folds is telemetry written to /dev/null [native-telemetry]
- **slo-coverage** (lives here, ISSUE 17) — every SLO objective in
  obs/slo.py's DEFAULT_OBJECTIVES must bind a metric family
  registered in stats.py and be documented in the monitoring docs,
  and every row of the README's "SLO objectives" table must name an
  objective that still exists — an SLO over an unregistered family
  evaluates no-data-ok forever, and a stale doc row promises a
  guarantee nobody evaluates [slo-coverage]

tests/unit/test_static_suite.py runs :func:`run` repo-clean as the
single tier-1 gate, so an analyzer added to ``PASSES`` is gated from
the commit that adds it.  To add a pass: write ``lint(root) ->
[str]`` in a tools/ module, append ``(name, fn)`` to ``PASSES``, and
add a fixture test proving the rule fires.

``--json`` (ISSUE 15 satellite) emits the machine-readable form —
per-pass finding lists, counts, and wall-clock ms — so the CI log is
greppable and a slow pass is attributable:

    python -m tools.static_suite --json | jq '.passes[] | {name, ms}'
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
import time
from typing import Callable, List, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import analysis_gate  # noqa: E402
import concurrency_lint  # noqa: E402
import durability_lint  # noqa: E402
import trace_lint  # noqa: E402

#: the one shared path list: everything the hygiene pass sweeps.  The
#: deeper passes (trace_lint / concurrency_lint) take the repo root
#: and restrict themselves to the package dirs they understand.
SUITE_PATHS = analysis_gate.DEFAULT_PATHS

#: metric-class constructors whose first argument is the family name
_METRIC_CLASSES = ("Counter", "Gauge", "LabeledGauge", "Histogram",
                   "LabeledHistogram")

#: documentation surfaces a metric family must appear in (either)
_DASHBOARD_DOCS = (
    os.path.join("monitoring", "antidote-tpu-dashboard.json"),
    os.path.join("monitoring", "README.md"),
)


def _gate(root: str) -> List[str]:
    from pathlib import Path
    return [f"{path}:{line}: [{code}] {msg}"
            for path, line, code, msg
            in analysis_gate.run(SUITE_PATHS, root=Path(root))]


def lint_stats_dashboard(root: str) -> List[str]:
    """Every metric family name registered in antidote_tpu/stats.py
    must appear in the packaged Grafana dashboard or the monitoring
    README — a registered-but-undocumented family is invisible
    exactly when someone needs it (PR 5-9 hand-kept this mapping)."""
    stats_py = os.path.join(root, "antidote_tpu", "stats.py")
    if not os.path.exists(stats_py):
        return [f"antidote_tpu/stats.py: [stats-dashboard] missing — "
                "the metrics registry moved?"]
    with open(stats_py) as f:
        tree = ast.parse(f.read(), filename=stats_py)
    families: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and getattr(node.func, "id", None) in _METRIC_CLASSES
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            families.append((node.args[0].value, node.lineno))
    corpus = ""
    missing_docs = []
    for rel in _DASHBOARD_DOCS:
        path = os.path.join(root, rel)
        if os.path.exists(path):
            with open(path) as f:
                corpus += f.read()
        else:
            missing_docs.append(rel)
    if not corpus:
        return [f"{' / '.join(missing_docs)}: [stats-dashboard] no "
                "dashboard docs found — the monitoring/ surface moved?"]
    problems = []
    for name, lineno in sorted(families):
        if name not in corpus:
            problems.append(
                f"antidote_tpu/stats.py:{lineno}: [stats-dashboard] "
                f"metric family {name!r} is registered but appears in "
                f"neither {' nor '.join(_DASHBOARD_DOCS)} — add a "
                "panel or document it in the README")
    return problems


#: the three surfaces the native-telemetry pass joins (ISSUE 16)
_TEL_RING_H = os.path.join("antidote_tpu", "native", "tel_ring.h")
_NATIVEOBS_PY = os.path.join("antidote_tpu", "obs", "nativeobs.py")

_TEL_EV_RE = re.compile(r"\bTEL_EV_([A-Z0-9_]+)\s*=\s*(\d+)")


def _registered_families(root: str) -> List[str]:
    """Family names registered in antidote_tpu/stats.py (the same
    extraction lint_stats_dashboard walks), [] if the file moved."""
    stats_py = os.path.join(root, "antidote_tpu", "stats.py")
    if not os.path.exists(stats_py):
        return []
    with open(stats_py) as f:
        tree = ast.parse(f.read(), filename=stats_py)
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and getattr(node.func, "id", None) in _METRIC_CLASSES
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.append(node.args[0].value)
    return out


def lint_native_telemetry(root: str) -> List[str]:
    """Join the three native-telemetry surfaces (ISSUE 16): every C++
    event kind (``TEL_EV_*`` in native/tel_ring.h) must have a decode
    entry in obs/nativeobs.py's EVENT_KINDS, fold into >= 1 family in
    EVENT_FAMILIES, and each such family must be BOTH registered in
    stats.py AND present in the dashboard docs.  A kind the event
    threads record but the drain never folds — or folds into a family
    nobody registered or charted — is telemetry written to /dev/null,
    which is exactly the hole this plane exists to close."""
    header = os.path.join(root, _TEL_RING_H)
    obs_py = os.path.join(root, _NATIVEOBS_PY)
    problems = []
    if not os.path.exists(header):
        return [f"{_TEL_RING_H}: [native-telemetry] missing — the "
                "native telemetry ring moved?"]
    if not os.path.exists(obs_py):
        return [f"{_NATIVEOBS_PY}: [native-telemetry] missing — the "
                "drain/fold module moved?"]
    with open(header) as f:
        cpp_kinds = {int(num): name
                     for name, num in _TEL_EV_RE.findall(f.read())}
    if not cpp_kinds:
        return [f"{_TEL_RING_H}: [native-telemetry] no TEL_EV_* enum "
                "constants parsed — the rule would be vacuous"]
    with open(obs_py) as f:
        tree = ast.parse(f.read(), filename=obs_py)
    # module-level EV_* ints, then the two tables keyed through them
    ev_consts, event_kinds, event_families = {}, {}, {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        tgt = node.targets[0].id
        if (tgt.startswith("EV_")
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            ev_consts[tgt] = node.value.value
        elif tgt == "EVENT_KINDS" and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                kid = (ev_consts.get(k.id) if isinstance(k, ast.Name)
                       else k.value if isinstance(k, ast.Constant)
                       else None)
                if kid is not None and isinstance(v, ast.Constant):
                    event_kinds[kid] = v.value
        elif tgt == "EVENT_FAMILIES" and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) \
                        and isinstance(v, (ast.Tuple, ast.List)):
                    event_families[k.value] = [
                        e.value for e in v.elts
                        if isinstance(e, ast.Constant)]
    registered = set(_registered_families(root))
    corpus = ""
    for rel in _DASHBOARD_DOCS:
        path = os.path.join(root, rel)
        if os.path.exists(path):
            with open(path) as f:
                corpus += f.read()
    for kid in sorted(cpp_kinds):
        cpp_name = f"TEL_EV_{cpp_kinds[kid]}"
        kind = event_kinds.get(kid)
        if kind is None:
            problems.append(
                f"{_TEL_RING_H}: [native-telemetry] C++ event kind "
                f"{cpp_name} (id {kid}) has no decode entry in "
                f"nativeobs.EVENT_KINDS — the drain renders it '?'")
            continue
        fams = event_families.get(kind, [])
        if not fams:
            problems.append(
                f"{_NATIVEOBS_PY}: [native-telemetry] event kind "
                f"{kind!r} ({cpp_name}) maps to no stats family in "
                "EVENT_FAMILIES — folded events vanish")
            continue
        for fam in fams:
            if fam not in registered:
                problems.append(
                    f"{_NATIVEOBS_PY}: [native-telemetry] family "
                    f"{fam!r} (kind {kind!r}) is not registered in "
                    "antidote_tpu/stats.py — the fold would KeyError "
                    "or count into nothing")
            if fam not in corpus:
                problems.append(
                    f"{_NATIVEOBS_PY}: [native-telemetry] family "
                    f"{fam!r} (kind {kind!r}) appears in neither "
                    f"{' nor '.join(_DASHBOARD_DOCS)} — add a panel "
                    "or document it in the README")
    # reverse direction: a Python-side kind id the C++ enum no longer
    # emits is dead decode code the next reader trips over
    for kid in sorted(set(event_kinds) - set(cpp_kinds)):
        problems.append(
            f"{_NATIVEOBS_PY}: [native-telemetry] EVENT_KINDS id "
            f"{kid} ({event_kinds[kid]!r}) has no TEL_EV_* constant "
            f"in {_TEL_RING_H} — stale decode entry")
    return problems


#: the surfaces the slo-coverage pass joins (ISSUE 17)
_SLO_PY = os.path.join("antidote_tpu", "obs", "slo.py")
_MONITORING_README = os.path.join("monitoring", "README.md")

#: first-column backticked name of a row in the README's
#: "SLO objectives" table
_SLO_ROW_RE = re.compile(r"^\|\s*`([A-Za-z0-9_]+)`")


def _slo_objectives(root: str):
    """(name, family, lineno) per Objective(...) entry in slo.py's
    DEFAULT_OBJECTIVES, parsed from the AST (keywords first, then
    positionals), or None when the module is missing."""
    path = os.path.join(root, _SLO_PY)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out: List[Tuple[str, str, int]] = []
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            targets = [node.target.id]
        if "DEFAULT_OBJECTIVES" not in targets:
            continue
        value = node.value
        if not isinstance(value, (ast.Tuple, ast.List)):
            continue
        for call in value.elts:
            if not (isinstance(call, ast.Call)
                    and getattr(call.func, "id", None) == "Objective"):
                continue
            fields = {}
            for pos, arg in zip(("name", "family"), call.args):
                if isinstance(arg, ast.Constant):
                    fields[pos] = arg.value
            for kw in call.keywords:
                if kw.arg in ("name", "family") \
                        and isinstance(kw.value, ast.Constant):
                    fields[kw.arg] = kw.value.value
            if "name" in fields and "family" in fields:
                out.append((str(fields["name"]), str(fields["family"]),
                            call.lineno))
    return out


def lint_slo_coverage(root: str) -> List[str]:
    """Join the SLO surfaces (ISSUE 17), both directions: every
    objective in obs/slo.py's DEFAULT_OBJECTIVES must bind a metric
    family actually registered in stats.py (an SLO over an
    unregistered family silently evaluates no-data-ok forever) and
    must be documented in the monitoring docs; and every row of the
    README's "SLO objectives" table must name an objective that still
    exists (a stale doc row promises a guarantee nobody evaluates)."""
    objectives = _slo_objectives(root)
    if objectives is None:
        return [f"{_SLO_PY}: [slo-coverage] missing — the SLO "
                "module moved?"]
    if not objectives:
        return [f"{_SLO_PY}: [slo-coverage] no Objective entries "
                "parsed from DEFAULT_OBJECTIVES — the rule would be "
                "vacuous"]
    problems: List[str] = []
    registered = set(_registered_families(root))
    corpus = ""
    for rel in _DASHBOARD_DOCS:
        path = os.path.join(root, rel)
        if os.path.exists(path):
            with open(path) as f:
                corpus += f.read()
    names = set()
    for name, family, lineno in objectives:
        names.add(name)
        if family not in registered:
            problems.append(
                f"{_SLO_PY}:{lineno}: [slo-coverage] objective "
                f"{name!r} binds family {family!r} which is not "
                "registered in antidote_tpu/stats.py — it would "
                "evaluate no-data-ok forever")
        if name not in corpus:
            problems.append(
                f"{_SLO_PY}:{lineno}: [slo-coverage] objective "
                f"{name!r} appears in neither "
                f"{' nor '.join(_DASHBOARD_DOCS)} — document the SLO "
                "in the README's \"SLO objectives\" table")
    # reverse direction: the README's objectives table must not name
    # objectives that no longer exist
    readme = os.path.join(root, _MONITORING_README)
    documented = []
    in_table = False
    if os.path.exists(readme):
        with open(readme) as f:
            for i, line in enumerate(f, 1):
                if re.match(r"^#+ .*SLO objectives", line):
                    in_table = True
                    continue
                if in_table and line.startswith("#"):
                    in_table = False
                if not in_table:
                    continue
                m = _SLO_ROW_RE.match(line)
                if m:
                    documented.append((m.group(1), i))
    if not documented:
        problems.append(
            f"{_MONITORING_README}: [slo-coverage] no \"SLO "
            "objectives\" table rows found — the docs surface the "
            "reverse direction checks is missing")
    for doc_name, lineno in documented:
        if doc_name not in names:
            problems.append(
                f"{_MONITORING_README}:{lineno}: [slo-coverage] "
                f"documented objective {doc_name!r} does not exist in "
                f"{_SLO_PY} DEFAULT_OBJECTIVES — stale doc row")
    return problems


#: (name, lint) — every pass the suite runs; the tier-1 gate iterates
#: THIS list, so appending here is all a new analyzer needs for CI
PASSES: Tuple[Tuple[str, Callable[[str], List[str]]], ...] = (
    ("analysis_gate", _gate),
    ("trace_lint", trace_lint.lint),
    ("concurrency_lint", concurrency_lint.lint),
    ("durability_lint", durability_lint.lint),
    ("stats-dashboard", lint_stats_dashboard),
    ("native-telemetry", lint_native_telemetry),
    ("slo-coverage", lint_slo_coverage),
)


def run_timed(root: str | None = None) -> List[dict]:
    """Every pass with its findings, count and wall-clock ms — the
    machine-readable form ``--json`` emits, and what :func:`run`
    flattens.  Timing rides along so a slow pass in CI is attributable
    to its analyzer instead of 'the suite got slow'."""
    root = root or repo_root()
    out: List[dict] = []
    for name, fn in PASSES:
        t0 = time.perf_counter()
        findings = fn(root)
        out.append({
            "name": name,
            "findings": findings,
            "count": len(findings),
            "ms": round((time.perf_counter() - t0) * 1e3, 2),
        })
    return out


def run(root: str | None = None) -> List[str]:
    """Every pass's findings, prefixed with the pass name."""
    return [f"{p['name']}: {f}"
            for p in run_timed(root) for f in p["findings"]]


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    as_json = "--json" in argv
    rest = [a for a in argv if a != "--json"]
    root = rest[0] if rest else repo_root()
    if as_json:
        passes = run_timed(root)
        total = sum(p["count"] for p in passes)
        print(json.dumps({
            "ok": total == 0,
            "total_findings": total,
            "total_ms": round(sum(p["ms"] for p in passes), 2),
            "passes": passes,
        }, indent=2))
        return 1 if total else 0
    problems = run(root)
    if problems:
        print(f"static_suite: {len(problems)} finding(s) across "
              f"{len(PASSES)} passes:", file=sys.stderr)
        for p in problems:
            print(f"  {p}", file=sys.stderr)
        return 1
    print(f"static_suite: OK — {len(PASSES)} passes clean "
          f"({', '.join(n for n, _ in PASSES)})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
